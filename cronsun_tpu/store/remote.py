"""Networked coordination store: MemStore served over TCP.

The reference's topology is N machines talking to etcd over gRPC
(client.go:24-114, watches at job.go:369-371).  This module provides the
same boundary for the rebuild: :class:`StoreServer` exposes a MemStore's
full API (revisioned KV, prefix watches with prev-kv, leases, CAS txns)
over a line-delimited JSON protocol, and :class:`RemoteStore` is a
drop-in client with the identical Python surface — every component
(scheduler, agents, web, noticer) runs unchanged against either.

Wire protocol (one JSON object per line, UTF-8):

    client -> server   {"i": <id>, "o": <op>, "a": [args...]}
    server -> client   {"i": <id>, "r": <result>}            (ok)
                       {"i": <id>, "e": <msg>, "k": <kind>}  (error)
                       {"w": <wid>, "evs": [<event>...]}     (watch push,
                                                              batched)
                       {"w": <wid>, "ev": <event>}           (legacy
                                                              single push)

KV wire form: [key, value, create_rev, mod_rev, lease]
Event wire form: [type, kv, prev_kv-or-null]

Design notes:
- One reader thread per client demuxes RPC replies (by id) and watch
  events (by wid).  Calls are synchronous RPCs; any thread may call.
- Watch pushes are BATCHED: one pump thread per connection drains every
  ready watcher per wakeup and ships one {"w", "evs"} frame per watcher
  (one sendall for the whole wakeup) — a dispatch burst of K events
  costs a handful of wire frames, not K serialized lines.  Clients
  accept both the batched and the legacy single-event form.
- Leases live server-side and expire by TTL whether or not the client is
  connected — exactly etcd's behaviour, and what node-death detection
  relies on (noticer.go:172-200).  A dropped connection closes its
  watches but never its leases.
- ``put_many`` batches order publication into one round trip (the
  scheduler's dispatch plane writes whole windows at once).
"""

from __future__ import annotations

import json
import queue
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

from .. import log
from ..chaos.hooks import hooks as _chaos
from ..core.backoff import RECONNECT
from .memstore import CompactedError, DELETE, LossyEventStream, PUT, \
    Event, KV, MemStore, WatchLost, Watcher
from .wire import LineJsonHandler


def _kv_wire(kv: Optional[KV]):
    if kv is None:
        return None
    return [kv.key, kv.value, kv.create_rev, kv.mod_rev, kv.lease]


def _kv_unwire(w) -> Optional[KV]:
    if w is None:
        return None
    return KV(key=w[0], value=w[1], create_rev=w[2], mod_rev=w[3],
              lease=w[4])


def _ev_wire(ev: Event):
    return [ev.type, _kv_wire(ev.kv), _kv_wire(ev.prev_kv)]


def _ev_unwire(w) -> Event:
    return Event(type=w[0], kv=_kv_unwire(w[1]), prev_kv=_kv_unwire(w[2]))


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------

_OPS = ("put", "put_many", "get", "get_many", "get_prefix",
        "get_prefix_page", "count_prefix", "delete",
        "delete_prefix", "delete_many", "put_if_absent", "put_if_mod_rev",
        "claim", "claim_many", "claim_bundle", "claim_bundle_many",
        "grant", "keepalive", "revoke", "lease_ttl_remaining", "op_stats",
        "snapshot", "rev")

# ops a replica-group FOLLOWER refuses (leases and fences are granted
# only by the leader — the replication plane's exactly-once contract);
# under --repl-ack quorum these also wait for >= 1 follower ack before
# the success reply goes out
_MUTATING = frozenset({
    "put", "put_many", "delete", "delete_prefix", "delete_many",
    "put_if_absent", "put_if_mod_rev", "claim", "claim_many",
    "claim_bundle", "claim_bundle_many", "grant", "keepalive", "revoke"})


class _Conn(LineJsonHandler):
    def setup(self):
        super().setup()
        # register with the owning server so stop()/kill() can sever
        # established connections (handler threads are daemonic: without
        # this a "stopped" server keeps serving its open sockets, which
        # makes a killed replica leader look alive to its followers)
        conns = getattr(self.server, "conns", None)
        if conns is not None:
            with self.server.conns_lock:     # type: ignore[attr-defined]
                conns.add(self)
        self.watchers: Dict[int, Watcher] = {}
        # one BATCHING pump per connection (not a thread per watcher):
        # watchers signal readiness here; the pump drains every ready
        # stream per wakeup and ships one {"w", "evs"} frame per watcher
        # in a single send
        self._ready: "queue.Queue[int]" = queue.Queue()
        self._pump_thread: Optional[threading.Thread] = None

    # per-send coalescing cap (the native writer uses the same bound): a
    # catch-up replay or expiry burst of 100k events must not serialize
    # into one multi-MB buffer while holding the write lock — RPC
    # replies on this connection would stall behind the whole send
    SEND_CHUNK = 256 << 10

    def _send_batch(self, objs):
        buf = bytearray()
        for o in objs:
            buf += (json.dumps(o, separators=(",", ":")) + "\n").encode()
            if len(buf) >= self.SEND_CHUNK:
                self._send_bytes(bytes(buf))
                buf.clear()
        if buf:
            self._send_bytes(bytes(buf))

    def _send_bytes(self, data: bytes):
        with self.wlock:
            try:
                self.request.sendall(data)
            except OSError:
                self.alive = False

    def _pump(self):
        """Forward every watcher's events to the client until the
        connection dies: per wakeup, drain ALL ready watchers and ship
        one batched frame per watcher.  A slow-consumer cancellation
        propagates as a lost notification so the client can re-list +
        re-watch instead of starving silently."""
        store: MemStore = self.server.store      # type: ignore[attr-defined]
        while self.alive:
            try:
                wids = {self._ready.get(timeout=0.25)}
            except queue.Empty:
                continue
            while True:                     # coalesce the whole wakeup
                try:
                    wids.add(self._ready.get_nowait())
                except queue.Empty:
                    break
            frames = []
            nev = 0
            for wid in wids:
                w = self.watchers.get(wid)
                if w is None:
                    continue
                try:
                    evs = w.drain()
                except WatchLost:
                    frames.append({"w": wid, "lost": True})
                    self.watchers.pop(wid, None)
                    continue
                if evs:
                    # bounded frames: a catch-up replay can drain tens
                    # of thousands of events in one wakeup — ship them
                    # as a few capped frames, not one giant line
                    for i in range(0, len(evs), 2048):
                        chunk = evs[i:i + 2048]
                        frames.append(
                            {"w": wid,
                             "evs": [_ev_wire(e) for e in chunk]})
                    nev += len(evs)
                if w.lost:
                    # the buffered tail is out; come back for the
                    # WatchLost -> lost frame on the next wakeup
                    self._ready.put(wid)
            if frames:
                self._send_batch(frames)
                store.op_count("watch_frames", len(frames))
                if nev:
                    store.op_count("watch_events", nev)

    def dispatch(self, rid, op, args):
        store: MemStore = self.server.store      # type: ignore[attr-defined]
        try:
            if op == "watch":
                prefix, start_rev = args[0], args[1]
                events = args[2] if len(args) > 2 else ""
                w = store.watch(prefix, start_rev=start_rev or 0,
                                events=events)
                wid = rid
                self.watchers[wid] = w
                w.on_ready = lambda _w, q=self._ready, i=wid: q.put(i)
                if self._pump_thread is None:
                    self._pump_thread = threading.Thread(
                        target=self._pump, daemon=True,
                        name="store-pump")
                    self._pump_thread.start()
                # the start_rev replay filled the queue BEFORE on_ready
                # was attached: nudge the pump once unconditionally
                self._ready.put(wid)
                self._send({"i": rid, "r": wid})
            elif op == "unwatch":
                w = self.watchers.pop(args[0], None)
                if w:
                    w.close()
                self._send({"i": rid, "r": True})
            elif op == "repl_status":
                mgr = getattr(self.server, "repl", None)
                self._send({"i": rid, "r": {"enabled": False}
                            if mgr is None else mgr.status()})
            elif op in ("repl_hello", "repl_pull", "repl_ack",
                        "repl_snapshot"):
                mgr = getattr(self.server, "repl", None)
                if mgr is None:
                    self._send({"i": rid, "e": f"{op}: replication "
                                "disabled on this server",
                                "k": "RuntimeError"})
                else:
                    fn = {"repl_hello": mgr.hello,
                          "repl_pull": mgr.pull,
                          "repl_ack": mgr.ack,
                          "repl_snapshot": mgr.snapshot_dump}[op]
                    self._send({"i": rid, "r": fn(*args)})
            elif op in _OPS:
                mgr = getattr(self.server, "repl", None)
                mutating = mgr is not None and op in _MUTATING
                if mutating and mgr.role() != "leader":
                    # leases/fences/writes are the LEADER's alone: the
                    # client rotates to the leader on this error
                    raise NotLeaderError(
                        f"{op}: this replica is a follower")
                r = getattr(store, op)(*args)
                if op == "get":
                    r = _kv_wire(r)
                elif op in ("get_prefix", "get_prefix_page", "get_many"):
                    r = [_kv_wire(kv) for kv in r]
                if mutating and mgr.ack_mode == "quorum":
                    # durability before the ack: the reply waits until
                    # >= 1 follower's cursor covers this op's records.
                    # On timeout the op is applied locally but reported
                    # FAILED under the DISTINCT QuorumTimeout kind —
                    # clients must not blindly retry (grant is not
                    # idempotent; put/delete double-bump the revision),
                    # but a failover cannot lose a write we never
                    # acked.
                    seq = mgr.log.seq
                    if not mgr.ack_wait(seq):
                        self._send({
                            "i": rid,
                            "e": f"{op}: applied locally but no "
                                 f"follower ack of seq {seq} within "
                                 f"{mgr.ack_timeout}s (quorum mode)",
                            "k": "QuorumTimeout"})
                        return
                self._send({"i": rid, "r": r})
            else:
                self._send({"i": rid, "e": f"unknown op {op!r}",
                            "k": "ValueError"})
        except NotLeaderError as e:
            self._send({"i": rid, "e": str(e), "k": "NotLeader"})
        except KeyError as e:
            self._send({"i": rid, "e": str(e), "k": "KeyError"})
        except CompactedError as e:
            self._send({"i": rid, "e": str(e), "k": "CompactedError"})
        except WatchLost as e:
            self._send({"i": rid, "e": str(e), "k": "WatchLost"})
        except Exception as e:  # noqa: BLE001 — report, keep serving
            self._send({"i": rid, "e": f"{type(e).__name__}: {e}",
                        "k": "RuntimeError"})

    def finish(self):
        super().finish()    # retire the handshake watchdog (wire.py)
        self.alive = False
        conns = getattr(self.server, "conns", None)
        if conns is not None:
            with self.server.conns_lock:     # type: ignore[attr-defined]
                conns.discard(self)
        # snapshot: the pump thread pops lost watchers concurrently
        for w in list(self.watchers.values()):
            w.close()
        self.watchers.clear()


class StoreServer:
    """Serve a MemStore over TCP.  ``addr`` like ("127.0.0.1", 7070);
    port 0 picks a free port (see :attr:`port`)."""

    def __init__(self, store: Optional[MemStore] = None,
                 host: str = "127.0.0.1", port: int = 0, token: str = "",
                 sslctx=None):
        self.store = store or MemStore()
        self.store.start_sweeper()

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
        self._srv = _Server((host, port), _Conn)
        self._srv.conns = set()                      # type: ignore[attr-defined]
        self._srv.conns_lock = threading.Lock()      # type: ignore[attr-defined]
        self._srv.store = self.store                 # type: ignore[attr-defined]
        self._srv.token = token                      # type: ignore[attr-defined]
        self._srv.sslctx = sslctx                    # type: ignore[attr-defined]
        self._srv.repl = None                        # type: ignore[attr-defined]
        self.repl = None
        self.host, self.port = self._srv.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def attach_repl(self, mgr) -> "StoreServer":
        """Wire a repl.ReplManager into the dispatch plane: repl_* ops
        answer, followers refuse mutations, quorum ack gates replies.
        Attach before serving clients."""
        self.repl = mgr
        self._srv.repl = mgr                         # type: ignore[attr-defined]
        return self

    def start(self) -> "StoreServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="store-server")
        self._thread.start()
        return self

    def _sever_conns(self):
        with self._srv.conns_lock:           # type: ignore[attr-defined]
            conns = list(self._srv.conns)    # type: ignore[attr-defined]
        for c in conns:
            c.alive = False
            try:
                c.request.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.request.close()
            except OSError:
                pass

    def stop(self):
        if self.repl is not None:
            self.repl.stop()
        self._srv.shutdown()
        self._srv.server_close()
        self._sever_conns()
        if self._thread:
            self._thread.join(timeout=3)
        self.store.close()

    def kill(self):
        """Hard-kill (the in-process kill -9): stop accepting, sever
        every established connection mid-flight, and abandon the store
        WITHOUT closing it — no flush, no sweeper shutdown handshake,
        no repl goodbye.  Followers see their pull connections die
        exactly as they would for a dead process; the chaos drills'
        leader-kill is built on this."""
        if self.repl is not None:
            self.repl._stop.set()     # silence the loop; no demote/ack
        self._srv.shutdown()
        self._srv.server_close()
        self._sever_conns()
        if self._thread:
            self._thread.join(timeout=3)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class RemoteWatcher(LossyEventStream):
    """Client-side watch stream; same surface (and WatchLost contract,
    via the shared LossyEventStream base) as memstore.Watcher."""

    def __init__(self, store: "RemoteStore", wid: int, prefix: str,
                 start_rev: int = 0, events: str = ""):
        super().__init__(prefix)
        self._store = store
        self._wid = wid
        self.start_rev = start_rev
        self.events = events       # "" all / "delete" only (re-watch too)
        self.last_rev = 0          # highest mod_rev seen (resume point)

    def _emit(self, ev: Event):
        if not self._closed:
            if ev.kv.mod_rev > self.last_rev:
                self.last_rev = ev.kv.mod_rev
            self._q.put(ev)

    def _mark_lost(self):
        """Server cancelled this stream (slow consumer): same WatchLost
        contract as the in-process Watcher."""
        self.lost = True
        self._closed = True
        self._store._watchers.pop(self._wid, None)
        self._q.put(None)

    def close(self):
        if self._closed:
            return
        self._closed = True
        self._store._unwatch(self._wid)
        self._q.put(None)


class RemoteStoreError(RuntimeError):
    pass


class NotLeaderError(RemoteStoreError):
    """The targeted replica is a follower: leases, fences, and writes
    belong to its group's leader (replication plane).  Replica-group
    clients rotate to the leader on this error."""


class QuorumTimeoutError(RemoteStoreError):
    """A ``--repl-ack quorum`` write was APPLIED on the leader but no
    follower acked it within the window — it is live locally and will
    ship when a follower catches up, it is just not known replicated.
    Distinct from a generic failure because a blind retry DOUBLE-
    APPLIES non-idempotent ops (``grant`` allocates a second lease;
    put/delete bump the revision and fire watch events twice):
    replica-group clients surface this instead of rotating, and the
    caller decides — re-read before re-granting, treat an idempotent
    overwrite as acceptable, or wait for the follower to rejoin."""


class RemoteStore:
    """TCP client with MemStore's exact API — scheduler/agent/web/noticer
    run unchanged against it (the rebuild's etcd clientv3,
    client.go:24-114).

    Self-healing: a dropped connection fails in-flight calls (callers see
    :class:`RemoteStoreError` and retry at their own cadence), then a
    background loop reconnects with backoff and re-establishes every open
    watch from its last seen revision — replaying the missed deltas.  If
    the server has compacted past that revision the watch resumes from
    the current revision and the gap is logged (callers that need
    completeness re-list, exactly like an etcd client)."""

    def __init__(self, host: str, port: int, timeout: float = 10.0,
                 reconnect: bool = True, token: str = "", sslctx=None,
                 tls_hostname: str = ""):
        self.host, self.port = host, port
        self._timeout = timeout
        self._reconnect = reconnect
        self._token = token
        self._sslctx = sslctx
        self._tls_hostname = tls_hostname
        self._wlock = threading.Lock()
        self._next_id = 1
        self._id_lock = threading.Lock()
        self._pending: Dict[int, dict] = {}
        self._pending_ev: Dict[int, threading.Event] = {}
        self._watchers: Dict[int, RemoteWatcher] = {}
        self._closed = False
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        # optional hook for replica-group clients (reconnect=False):
        # called once, with this store, when the connection dies
        # UNEXPECTEDLY — the group wrapper marks live watchers lost so
        # their consumers re-list through a freshly discovered leader
        # instead of starving on a closed-but-not-lost stream
        self.on_disconnect = None
        self._connect()

    # -- plumbing ----------------------------------------------------------

    def _connect(self):
        sock = socket.create_connection((self.host, self.port), timeout=30)
        if self._sslctx is not None:
            from ..tlsutil import wrap_client
            sock = wrap_client(sock, self._sslctx, self._tls_hostname)
        sock.settimeout(None)
        rfile = sock.makefile("rb")
        if self._sslctx is not None:
            # First round trip runs SYNCHRONOUSLY, before the reader
            # thread exists.  An OpenSSL connection is not a thread-safe
            # object, and right after the handshake the post-handshake
            # records (TLS 1.3 NewSessionTicket) are processed inside
            # the connection's first SSL_read — a concurrent SSL_write
            # from the calling thread raced that read and intermittently
            # swallowed the first frame, which surfaced as the server's
            # auth-timeout watchdog severing an apparently-healthy
            # connection ~10 s in (the test_tls flake: first-rpc
            # failures on fresh TLS connections under repetition).  One
            # synchronous auth round trip drains those records single-
            # threaded; afterwards the usual one-reader + serialized-
            # writers discipline holds.
            self._handshake_rpc(sock, rfile)
            threading.Thread(target=self._read_loop, args=(sock, rfile),
                             daemon=True,
                             name="remote-store-reader").start()
        else:
            threading.Thread(target=self._read_loop, args=(sock, rfile),
                             daemon=True,
                             name="remote-store-reader").start()
            if self._token:
                # authenticate BEFORE publishing the socket: a
                # concurrent _call sending ahead of the handshake would
                # hit the server's first-frame-must-auth rule and get
                # the fresh connection closed under us (reconnect churn
                # on every heal)
                self._call("auth", self._token, sock_override=sock)
        self._sock = sock
        self._rfile = rfile

    def _handshake_rpc(self, sock, rfile):
        """One blocking auth round trip on the freshly wrapped TLS
        socket (no reader thread yet; open servers answer the auth op
        as a no-op, so this doubles as the post-handshake drain)."""
        data = (json.dumps({"i": 0, "o": "auth",
                            "a": [self._token] if self._token else [""]},
                           separators=(",", ":")) + "\n").encode()
        sock.settimeout(self._timeout)
        try:
            sock.sendall(data)
            line = rfile.readline()
        except OSError as e:
            raise RemoteStoreError(f"tls handshake rpc failed: {e}")
        finally:
            sock.settimeout(None)
        if not line:
            raise RemoteStoreError(
                "connection closed during handshake rpc")
        try:
            msg = json.loads(line)
        except ValueError:
            raise RemoteStoreError("malformed handshake rpc reply")
        if "e" in msg:
            raise RemoteStoreError(msg["e"])

    def _read_loop(self, sock, rfile):
        while not self._closed:
            try:
                line = rfile.readline()
            except OSError:
                break
            if not line:
                break
            try:
                msg = json.loads(line)
            except ValueError:   # JSONDecodeError or UnicodeDecodeError
                continue         # (binary garbage: TLS alert bytes from a
                                 # mis-dialed TLS server, line noise)
            if "w" in msg:
                w = self._watchers.get(msg["w"])
                if w is not None:
                    if msg.get("lost"):
                        w._mark_lost()
                    elif "evs" in msg:       # batched push (one frame,
                        for e in msg["evs"]:  # many events)
                            w._emit(_ev_unwire(e))
                    else:                    # legacy single-event push
                        w._emit(_ev_unwire(msg["ev"]))
                continue
            rid = msg.get("i")
            ev = self._pending_ev.get(rid)
            if ev is not None:
                self._pending[rid] = msg
                ev.set()
        # connection gone: unpublish the socket FIRST (new calls fail
        # fast instead of sendall-ing into a dead TCP buffer and waiting
        # out the full rpc timeout with no reader left to fail them),
        # then fail in-flight calls
        if self._sock is sock:
            self._sock = None
        for rid, ev in list(self._pending_ev.items()):
            self._pending.setdefault(rid, {"e": "connection closed",
                                           "k": "RemoteStoreError"})
            ev.set()
        if self._closed or not self._reconnect:
            unexpected = not self._closed
            self._finalize()
            if unexpected:
                cb = self.on_disconnect
                if cb is not None:
                    try:
                        cb(self)
                    except Exception:  # noqa: BLE001 — reader must die
                        pass           # clean regardless of the hook
            return
        threading.Thread(target=self._heal, daemon=True,
                         name="remote-store-heal").start()

    def _finalize(self):
        self._closed = True
        for w in list(self._watchers.values()):
            w._closed = True
            w._q.put(None)

    def _heal(self):
        attempt = 0
        while not self._closed:
            try:
                self._connect()
                break
            except (OSError, RemoteStoreError) as e:
                # RemoteStoreError here is an auth refusal on the fresh
                # connection (server restarted with a new token?) — keep
                # retrying with backoff rather than dying silently
                if isinstance(e, RemoteStoreError):
                    log.errorf("store reconnect refused: %s", e)
                attempt += 1
                RECONNECT.sleep(attempt)   # 0.2 s doubling, 2 s cap
        if self._closed:
            self._finalize()
            return
        # re-establish watches, resuming after the last delivered event
        for wid, w in list(self._watchers.items()):
            if w._closed:
                continue
            resume = w.last_rev + 1 if w.last_rev else 0
            try:
                try:
                    self._call("watch", w.prefix, resume, w.events,
                               rid=wid)
                except (CompactedError, WatchLost):
                    # the gap is unrecoverable: deltas are gone.  Don't
                    # silently re-watch from current — surface WatchLost
                    # so the consumer re-lists (anti-entropy), exactly
                    # like the slow-consumer cancellation path.
                    log.warnf("watch %r resume rev %d compacted; "
                              "consumer must re-list", w.prefix, resume)
                    w._mark_lost()
            except Exception as e:  # noqa: BLE001 — ANY re-establish
                # failure (timeout, refused, reply lost, unexpected)
                # leaves this stream NOT live: mark it LOST so the
                # consumer re-lists, exactly like the compacted-resume
                # path.  Logging alone left a silently dead watcher —
                # an agent's dispatch stream starved with no signal
                # until its leased orders expired (found by the
                # shard_partition drill once per-shard publish lanes
                # shifted the heal's timing).
                log.errorf("watch %r re-establish failed (%s); marking "
                           "LOST for consumer re-list", w.prefix, e)
                w._mark_lost()
        log.infof("store connection re-established (%s:%d)",
                  self.host, self.port)

    def _call(self, op: str, *args, rid: Optional[int] = None,
              sock_override=None):
        if self._closed:
            raise RemoteStoreError("store connection closed")
        # deterministic fault injection (chaos plane, env-gated off in
        # production): a 'timeout' fault fails the RPC before anything
        # reaches the wire; a 'reply_lost' fault lets the op APPLY
        # server-side and fails the reply path — the
        # applied-but-indeterminate shape every degraded ladder must
        # survive; a 'delay' fault stalls the caller (browned-out wire)
        act = _chaos.intercept("store.rpc", op) if _chaos.armed else None
        if act is not None:
            act.pre(RemoteStoreError, op)
        if rid is None:
            with self._id_lock:
                rid = self._next_id
                self._next_id += 1
        done = threading.Event()
        self._pending_ev[rid] = done
        data = (json.dumps({"i": rid, "o": op, "a": list(args)},
                           separators=(",", ":")) + "\n").encode()
        try:
            sock = sock_override or self._sock
            if sock is None:
                raise RemoteStoreError("store disconnected")
            try:
                with self._wlock:
                    sock.sendall(data)
            except OSError as e:
                raise RemoteStoreError(f"send failed: {e}")
            if self._sock is not sock and sock_override is None \
                    and not done.is_set():
                # the connection died between our socket read and the
                # send: its reader's in-flight sweep ran before this rid
                # registered a reply could reach, so nobody will ever
                # fail it — a sendall into the dead socket's buffer
                # "succeeds" and would wait out the whole rpc timeout
                raise RemoteStoreError("connection lost mid-call")
            if not done.wait(self._timeout):
                raise RemoteStoreError(f"rpc timeout: {op}")
            msg = self._pending.pop(rid, None)
            if msg is None:
                # the reply vanished between done.set and this pop: a
                # FIXED-rid call (the heal path re-watches with
                # rid=wid) can collide with a previous attempt's
                # timed-out call on the same rid — its finally clause
                # sweeps _pending[rid] from under us.  A failed RPC,
                # never a local KeyError crashing the caller (a crashed
                # heal thread used to leave every remaining watcher
                # silently dead).
                raise RemoteStoreError(f"rpc reply lost: {op}")
        finally:
            self._pending_ev.pop(rid, None)
            self._pending.pop(rid, None)
        if "e" in msg:
            kind = msg.get("k")
            if kind == "KeyError":
                raise KeyError(msg["e"])
            if kind == "CompactedError":
                raise CompactedError(msg["e"])
            if kind == "WatchLost":
                raise WatchLost(msg["e"])
            if kind == "NotLeader":
                raise NotLeaderError(msg["e"])
            if kind == "QuorumTimeout":
                raise QuorumTimeoutError(msg["e"])
            raise RemoteStoreError(msg["e"])
        if act is not None:
            act.post(RemoteStoreError, op)   # applied; reply "lost"
        return msg.get("r")

    # -- KV ----------------------------------------------------------------

    def put(self, key: str, value: str, lease: int = 0) -> int:
        return self._call("put", key, value, lease)

    def put_many(self, items, lease: int = 0) -> int:
        return self._call("put_many", list(items), lease)

    def get(self, key: str) -> Optional[KV]:
        return _kv_unwire(self._call("get", key))

    def get_many(self, keys) -> List[Optional[KV]]:
        return [_kv_unwire(w) for w in self._call("get_many", list(keys))]

    def get_prefix(self, prefix: str) -> List[KV]:
        return [_kv_unwire(w) for w in self._call("get_prefix", prefix)]

    def get_prefix_page(self, prefix: str, start_after: str = "",
                        limit: int = 50_000) -> List[KV]:
        return [_kv_unwire(w) for w in self._call(
            "get_prefix_page", prefix, start_after, limit)]

    def get_prefix_paged(self, prefix: str, page: int = 50_000):
        """Iterate a prefix in bounded pages.  A 1M-key prefix as ONE
        get_prefix reply is a multi-hundred-MB line whose json parse
        holds the GIL for seconds (starving every other thread in the
        process — measured on the scheduler's anti-entropy listings);
        paging bounds the reply, the parse slice, and peak memory.
        Falls back to one-shot get_prefix on servers predating the op.
        Pages are individually consistent; the full iteration has the
        usual range-pagination read skew."""
        page = max(1, page)     # servers clamp to >= 1; an unclamped 0
        start_after = ""        # here would never satisfy len < page
        while True:
            try:
                kvs = self.get_prefix_page(prefix, start_after, page)
            except RemoteStoreError as e:
                if "unknown op" in str(e) and not start_after:
                    yield from self.get_prefix(prefix)
                    return
                raise
            yield from kvs
            if len(kvs) < page:
                return
            start_after = kvs[-1].key

    def count_prefix(self, prefix: str) -> int:
        return self._call("count_prefix", prefix)

    def delete(self, key: str) -> bool:
        return self._call("delete", key)

    def delete_prefix(self, prefix: str) -> int:
        return self._call("delete_prefix", prefix)

    def delete_many(self, keys) -> int:
        return self._call("delete_many", list(keys))

    # -- txns --------------------------------------------------------------

    def put_if_absent(self, key: str, value: str, lease: int = 0) -> bool:
        return self._call("put_if_absent", key, value, lease)

    def put_if_mod_rev(self, key: str, value: str, mod_rev: int,
                       lease: int = 0) -> bool:
        return self._call("put_if_mod_rev", key, value, mod_rev, lease)

    def claim(self, fence_key: str, fence_val: str, fence_lease: int = 0,
              order_key: str = "", proc_key: str = "", proc_val: str = "",
              proc_lease: int = 0) -> bool:
        """Atomic fence+proc+order-consume (memstore.claim) in ONE round
        trip — the dispatch plane's per-execution hot op."""
        return self._call("claim", fence_key, fence_val, fence_lease,
                          order_key, proc_key, proc_val, proc_lease)

    def claim_many(self, items, fence_lease: int = 0,
                   proc_lease: int = 0) -> List[bool]:
        """Batched claim (memstore.claim_many): one round trip for a
        whole burst of due executions."""
        return self._call("claim_many", [list(it) for it in items],
                          fence_lease, proc_lease)

    def claim_bundle(self, order_key: str, items, fence_lease: int = 0,
                     proc_lease: int = 0) -> List[bool]:
        """Coalesced-order consume (memstore.claim_bundle): the whole
        (node, second) bundle — per-job fences, winners' proc keys, and
        the single reservation-key delete — in ONE round trip."""
        return self._call("claim_bundle", order_key,
                          [list(it) for it in items],
                          fence_lease, proc_lease)

    def claim_bundle_many(self, bundles, fence_lease: int = 0,
                          proc_lease: int = 0) -> List[List[bool]]:
        """Batched claim_bundle (memstore.claim_bundle_many): a whole
        backlog of due (node, second) bundles — the herd catch-up case —
        settled in ONE round trip.  ``bundles`` is
        [(order_key, items), ...]."""
        return self._call(
            "claim_bundle_many",
            [[ok, [list(it) for it in items]] for ok, items in bundles],
            fence_lease, proc_lease)

    def op_stats(self) -> dict:
        """Server-side per-op timing snapshot (memstore.op_stats)."""
        return self._call("op_stats")

    def snapshot(self) -> int:
        """Checkpoint plane: write a consistent point-in-time snapshot
        of the server's keyspace + lease table and truncate its WAL
        (memstore.snapshot / stored.cc snapshot).  Returns the
        snapshot's revision; errors if the server runs without a WAL."""
        return self._call("snapshot")

    def rev(self) -> int:
        """Current store revision (memstore.rev)."""
        return self._call("rev")

    def repl_status(self) -> dict:
        """Replication-plane status of this server: ``{"enabled":
        False}`` on unreplicated servers, else role / fencing epoch /
        cursor / applied revision / lag (repl.ReplManager.status)."""
        return self._call("repl_status")

    # -- leases ------------------------------------------------------------

    def grant(self, ttl: float) -> int:
        return self._call("grant", ttl)

    def keepalive(self, lease_id: int) -> bool:
        return self._call("keepalive", lease_id)

    def revoke(self, lease_id: int) -> bool:
        return self._call("revoke", lease_id)

    def lease_ttl_remaining(self, lease_id: int) -> Optional[float]:
        return self._call("lease_ttl_remaining", lease_id)

    # -- watch -------------------------------------------------------------

    def watch(self, prefix: str, start_rev: int = 0,
              events: str = "") -> RemoteWatcher:
        with self._id_lock:
            wid = self._next_id          # reserve the id we'll rpc with
            self._next_id += 1
        # register the watcher BEFORE the rpc returns so no event races
        # past the registration (the server keys pushes by the request id)
        w = RemoteWatcher(self, wid, prefix, start_rev, events)
        self._watchers[wid] = w
        try:
            self._call("watch", prefix, start_rev, events, rid=wid)
        except Exception:
            self._watchers.pop(wid, None)
            raise
        return w

    def _unwatch(self, wid: int):
        self._watchers.pop(wid, None)
        if not self._closed:
            try:
                self._call("unwatch", wid)
            except (RemoteStoreError, KeyError):
                pass

    def clone(self) -> "RemoteStore":
        """A fresh connection to the same server with the same auth/TLS
        — publisher lanes shard bulk writes over several of these."""
        return RemoteStore(self.host, self.port, timeout=self._timeout,
                          reconnect=self._reconnect, token=self._token,
                          sslctx=self._sslctx,
                          tls_hostname=self._tls_hostname)

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        self._closed = True
        sock = self._sock      # may be None mid-heal
        if sock is None:
            return
        try:
            sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        sock.close()

    # MemStore compat no-op: the server owns the sweeper
    def start_sweeper(self, interval: float = 0.2):
        pass
