"""Coordination store: the control plane.

The reference coordinates everything through an etcd v3 keyspace — watch
streams for pub/sub, leases for liveness/TTL, txns for CAS and locks, prefix
KV for state (reference client.go, SURVEY.md appendix).  This package keeps
that architecture but behind a small interface:

- :class:`memstore.MemStore` — a faithful in-process implementation of the
  semantics the system needs (create/mod revisions, prefix watch with prev-kv,
  lease expiry, compare-and-swap, create-if-absent locks).  It is both the
  test harness the reference never had (multi-node scenarios in one process,
  SURVEY.md §4) and a perfectly good single-host production store.
- a real etcd can be slotted in behind the same surface for multi-host
  deployments (adapter not bundled: no etcd client library in this
  environment).
"""

from .memstore import Event, KV, Lease, MemStore, Watcher  # noqa: F401
