"""Coordination store: the control plane.

The reference coordinates everything through an etcd v3 keyspace — watch
streams for pub/sub, leases for liveness/TTL, txns for CAS and locks, prefix
KV for state (reference client.go, SURVEY.md appendix).  This package keeps
that architecture but behind a small interface:

- :class:`memstore.MemStore` — a faithful in-process implementation of the
  semantics the system needs (create/mod revisions, prefix watch with prev-kv,
  lease expiry, compare-and-swap, create-if-absent locks).  It is both the
  test harness the reference never had (multi-node scenarios in one process,
  SURVEY.md §4) and a perfectly good single-host production store.
- :class:`remote.StoreServer` / :class:`remote.RemoteStore` — the same
  semantics over TCP: the server hosts a MemStore, the client is a drop-in
  replacement, and N processes/machines coordinate through it exactly as
  the reference's fleet does through etcd (client.go:24-114).
- a real etcd can also be slotted in behind the same surface (adapter not
  bundled: no etcd client library in this environment).
"""

from .memstore import (CompactedError, Event, KV, Lease,  # noqa: F401
                       MemStore, WatchLost, Watcher)
from .remote import RemoteStore, StoreServer  # noqa: F401
from .sharded import (ShardedStore, ShardedWatcher,  # noqa: F401
                      connect_sharded, shard_index, shard_token)
