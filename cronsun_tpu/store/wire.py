"""Shared line-JSON TCP plumbing for the two store servers.

The coordination store (store/remote.py) and the result store
(logsink/serve.py) speak the same transport: one JSON object per line,
``{"i", "o", "a"}`` requests, ``{"i", "r"}`` / ``{"i", "e"}`` replies,
and an optional first-frame shared-secret handshake.  This module holds
the pieces that must never drift apart — framing, the auth gate, and
the constant-time token comparison — so a protocol fix lands once.
"""

from __future__ import annotations

import hmac
import json
import socket
import socketserver
import ssl
import threading


def token_matches(presented, token: str) -> bool:
    """Constant-time token comparison over UTF-8 bytes.
    (``hmac.compare_digest`` on ``str`` raises TypeError for non-ASCII —
    an operator picking a token with an umlaut must not crash the auth
    path server-side.)"""
    return hmac.compare_digest(
        str(presented).encode("utf-8", "surrogatepass"),
        token.encode("utf-8", "surrogatepass"))


class LineJsonHandler(socketserver.BaseRequestHandler):
    """Base connection handler: line framing, locked writes, and the
    first-frame auth gate.  Subclasses implement ``dispatch(rid, op,
    args)`` (and may extend ``setup``/``finish``).  The server object
    must expose a ``token`` attribute ('' = open)."""

    # Per-connection WALL-CLOCK deadline on the TLS handshake plus (on
    # secured servers) the first auth frame: a client that connects and
    # stalls — or drip-feeds bytes to reset per-recv timeouts — must not
    # pin a handler thread forever.  Enforced by a watchdog timer that
    # shuts the raw socket down if the connection isn't authenticated by
    # the deadline (absolute, so partial progress never extends it).
    HANDSHAKE_TIMEOUT = 10.0

    def setup(self):
        self.wlock = threading.Lock()
        self.alive = True
        self.authed = False
        self._hs_lock = threading.Lock()
        self._hs_timer = None
        sslctx = getattr(self.server, "sslctx", None)
        if sslctx is not None or getattr(self.server, "token", ""):
            # watchdog only where a handshake can actually stall (TLS
            # and/or token servers) — open plaintext servers don't pay a
            # timer thread per accept.  The timer holds the FD NUMBER,
            # not the socket object: wrap_socket() detaches the raw
            # socket before the handshake, so an object reference would
            # go stale (EBADF) exactly when the deadline matters.
            fd = self.request.fileno()
            self._hs_timer = threading.Timer(self.HANDSHAKE_TIMEOUT,
                                             self._drop_unauthed, (fd,))
            self._hs_timer.daemon = True
            self._hs_timer.start()
        if sslctx is not None:
            # handshake runs here, in the per-connection thread (never in
            # the accept loop); a failed handshake — plaintext client,
            # wrong CA, missing client cert under mutual TLS — drops the
            # connection without killing the server
            try:
                self.request = sslctx.wrap_socket(self.request,
                                                  server_side=True)
            except (OSError, ssl.SSLError):
                self.alive = False
                self.rfile = None
                return
        self.rfile = self.request.makefile("rb")
        if not getattr(self.server, "token", ""):
            self._auth_ok()   # open (possibly TLS) server: TLS done, no
                              # auth frame to wait for

    def _auth_ok(self):
        with self._hs_lock:
            self.authed = True
            if self._hs_timer is not None:
                self._hs_timer.cancel()

    def _drop_unauthed(self, fd):
        """Watchdog body: sever an unauthenticated connection at the
        deadline.  Runs under the same lock as _auth_ok, and finish()
        marks the connection authed BEFORE socketserver closes the fd —
        so this can never shut down a recycled fd number."""
        with self._hs_lock:
            if self.authed:
                return
            self.alive = False
            try:
                s = socket.socket(fileno=fd)
            except OSError:
                return
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            finally:
                s.detach()   # fd still belongs to the connection

    def finish(self):
        self._auth_ok()   # retire the watchdog before the fd closes

    def _send(self, obj):
        data = (json.dumps(obj, separators=(",", ":")) + "\n").encode()
        with self.wlock:
            try:
                self.request.sendall(data)
            except OSError:
                self.alive = False

    def handle(self):
        if self.rfile is None:       # TLS handshake failed in setup
            return
        while self.alive:
            try:
                line = self.rfile.readline()
            except OSError:          # reset / TLS abort mid-read
                return
            if not line:
                return
            try:
                req = json.loads(line)
            except ValueError:
                # covers JSONDecodeError AND UnicodeDecodeError: binary
                # garbage (a TLS ClientHello against a plaintext port, a
                # port scanner) drops the connection, quietly
                return
            rid, op, args = req.get("i"), req.get("o"), req.get("a", [])
            if not self.authed:
                # first frame must authenticate; wrong token closes the
                # connection (the reference passes store credentials via
                # config, conf/conf.go:66-67, db/mgo.go:33-36)
                if op == "auth" and len(args) == 1 and \
                        token_matches(args[0], self.server.token):
                    self._auth_ok()                 # handshake complete
                    self._send({"i": rid, "r": True})
                    continue
                self._send({"i": rid, "e": "unauthenticated",
                            "k": "RuntimeError"})
                return
            if op == "auth":                 # no-op when unsecured
                self._send({"i": rid, "r": True})
                continue
            self.dispatch(rid, op, args)

    def dispatch(self, rid, op, args):  # pragma: no cover - abstract
        raise NotImplementedError
