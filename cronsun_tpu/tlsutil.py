"""TLS for the wire protocols (coordination store + result store).

The reference passes transport security through config: etcd gets a full
``clientv3.Config`` (TLS + username/password, conf/conf.go:66-67) and
Mongo gets credentials (db/mgo.go:33-36).  The rebuild's line-JSON
transport carries the shared-secret handshake (store/wire.py) for
authentication; this module adds the encryption half — flag-gated TLS on
both Python servers and both clients, with optional mutual TLS (the
server demands a client certificate signed by the fleet CA).

Deployment model: one private CA per fleet (``scripts/gen_certs.sh``),
server certs with SAN entries for every address agents dial, client
certs only when mutual TLS is on.  The native C++ servers
(cronsun-stored / cronsun-logd) speak plaintext and deploy behind a TLS
terminator (stunnel/haproxy) or on a trusted network — see
native/README.md.

Config surface (conf.py): ``store_tls`` / ``log_tls`` sections with
``ca``, ``cert``, ``key``, ``hostname``, ``client_ca``.  Clients use
``ca`` to verify the server (+ ``cert``/``key`` to present under mutual
TLS); servers use ``cert``/``key`` to serve and ``client_ca`` to demand
client certificates.  The client trust anchor and the server's
demand-client-certs knob are deliberately SEPARATE fields so one
section can be shared by every process in a fleet conf without
accidentally flipping on mutual TLS (a TLS client only sends its cert
when the server asks).  An empty section means plaintext — TLS never
turns on by accident — and a PARTIAL section raises at startup rather
than silently downgrading (a client with a cert but no CA must not
connect in clear).

Concurrency contract: every wire endpoint in this codebase touches its
socket from at most one reader thread plus mutex-serialized writers
(RemoteStore._read_loop vs _call under _wlock; the server handler
thread vs _pump under wlock).  That single-reader/locked-writer
discipline is what makes full-duplex TLS sound here: OpenSSL forbids
arbitrary concurrent use of one SSL*, but with renegotiation disabled
(OP_NO_RENEGOTIATION, set below) the read path never writes and the
write path never reads, so the two halves touch disjoint cipher state.
Neither endpoint ever initiates a TLS 1.3 KeyUpdate (CPython exposes no
API for it), so the read-path write-back that KeyUpdate would require
cannot occur between our own endpoints.  Code adding a second reader
thread per socket would break this contract — don't.
"""

from __future__ import annotations

import dataclasses
import ssl
from typing import Optional


@dataclasses.dataclass
class Tls:
    """One channel's TLS material.  All paths; "" disables that piece."""
    ca: str = ""         # client: fleet CA bundle the server must chain to
    cert: str = ""       # this endpoint's certificate chain
    key: str = ""        # this endpoint's private key
    hostname: str = ""   # client only: expected server SAN; "" skips
                         # hostname binding (IP fleets with a private CA)
    client_ca: str = ""  # server only: demand client certs chaining to
                         # this CA (mutual TLS)

    @property
    def client_enabled(self) -> bool:
        return bool(self.ca)

    @property
    def server_enabled(self) -> bool:
        return bool(self.cert)


def server_context(tls: Tls) -> Optional[ssl.SSLContext]:
    """Server-side context, or None when the section is empty.
    ``tls.client_ca`` set => mutual TLS (client certs required).  A
    partial section (key/client_ca without cert) raises instead of
    serving plaintext."""
    if not tls.server_enabled:
        if tls.key or tls.client_ca:
            raise ValueError(
                "TLS section has key/client_ca but no cert: refusing to "
                "serve plaintext on a half-configured channel")
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.options |= ssl.OP_NO_RENEGOTIATION   # see module docstring
    ctx.load_cert_chain(tls.cert, tls.key or None)
    if tls.client_ca:
        ctx.load_verify_locations(tls.client_ca)
        ctx.verify_mode = ssl.CERT_REQUIRED
    return ctx


def client_context(tls: Tls) -> Optional[ssl.SSLContext]:
    """Client-side context, or None when the section is empty.  The
    server cert is always verified against ``tls.ca``; hostname binding
    only when ``tls.hostname`` names the expected SAN.  A partial
    section (cert/key/hostname without ca) raises instead of silently
    connecting plaintext — that downgrade would put the shared token on
    the wire in clear."""
    if not tls.client_enabled:
        if tls.cert or tls.key or tls.hostname:
            raise ValueError(
                "TLS section has cert/key/hostname but no ca: refusing "
                "the silent plaintext downgrade")
        return None
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    ctx.minimum_version = ssl.TLSVersion.TLSv1_2
    ctx.options |= ssl.OP_NO_RENEGOTIATION   # see module docstring
    ctx.load_verify_locations(tls.ca)
    ctx.verify_mode = ssl.CERT_REQUIRED
    ctx.check_hostname = bool(tls.hostname)
    if tls.cert:
        ctx.load_cert_chain(tls.cert, tls.key or None)
    return ctx


def wrap_client(sock, ctx: Optional[ssl.SSLContext], hostname: str = ""):
    """Wrap an outbound socket; no-op when ctx is None."""
    if ctx is None:
        return sock
    return ctx.wrap_socket(sock, server_hostname=hostname or None)
