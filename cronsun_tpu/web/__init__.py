"""Web/API server: the management control surface.

The same /v1 REST semantics as the reference's gorilla/mux router
(web/routers.go:17-114) on the stdlib ThreadingHTTPServer — session auth
backed by the coordination store, role-gated admin endpoints, job/group
CRUD writing the same keyspace the scheduler watches, log queries against
the result store, and a single-file management UI at /ui/.
"""

from .server import ApiServer  # noqa: F401
from .sessions import SessionStore  # noqa: F401
