"""The /v1 REST API (reference web/routers.go:17-114 — all routes).

Stdlib ThreadingHTTPServer + a regex route table.  Handlers mirror the
reference's semantics:

- session login/logout + salted-hash accounts, bootstrap admin
  (web/authentication.go:20-133)
- role-gated admin account CRUD with force-logout on edit and the
  Unchangeable guard (web/administrator.go)
- job CRUD against the coordination store — CAS pause toggle, group-move
  delete, run-now via the once key, node resolution include ∪ groups −
  exclude (web/job.go)
- executing-list from the proc registry (web/job.go:278-337)
- group CRUD with the job-scrub on delete (web/node.go:78-139)
- paged/filtered log queries (web/job_log.go)
- overview + configurations (web/info.go, web/configuration.go)
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
from collections import OrderedDict
from http import HTTPStatus
from http.cookies import SimpleCookie
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .. import log, trace as _trace
from ..core import (
    Account, Group, Job, Keyspace, ROLE_ADMIN, TenantQuota,
    ValidationError, next_id, validate_dag)
from ..core.models import SloSpec, hash_password
from ..logsink import JobLogStore
from ..store.memstore import MemStore
from .sessions import Session, SessionStore
from .ui import INDEX_HTML

VERSION = "v0.1.0-tpu"
BOOTSTRAP_ADMIN = "admin@admin.com"
BOOTSTRAP_PASSWORD = "admin"


def _esc_label(v) -> str:
    """Prometheus exposition label-value escaping: backslash, double
    quote AND newline (the one the ad-hoc escapes missed — a tenant or
    op name containing a newline emitted a torn, unparseable line)."""
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class HttpError(Exception):
    def __init__(self, status: int, msg: str):
        super().__init__(msg)
        self.status = status
        self.msg = msg


class NotModified(HttpError):
    """304 via If-None-Match: the client's cached body is current.
    Carries the ETag so the transport can re-assert it; no body."""

    def __init__(self, etag: str):
        super().__init__(304, "not modified")
        self.etag = etag


class PlainText(str):
    """Handler return type served as text/plain instead of JSON
    (the /v1/metrics Prometheus exposition)."""


class SseStream:
    """Handler return type that takes over the transport: the HTTP
    layer sends ``text/event-stream`` headers and calls ``serve`` on
    the request thread, which writes events until the client drops,
    falls behind (terminal ``lost``), or the server drains (final
    ``bye`` with a long ``retry:``).  Event ``id:`` is the cursor
    vector — a reconnecting client resumes exactly-once via
    ``Last-Event-ID``."""

    def __init__(self, manager, client, replay: list):
        self.manager = manager
        self.client = client
        self.replay = replay

    def _event_bytes(self, ev) -> bytes:
        from .push import event_data_json
        self.client.advance(ev[0])
        cursor = ",".join(str(v) for v in self.client.vec)
        data = event_data_json(ev)
        return (f"id: {cursor}\nevent: log\ndata: {data}\n\n").encode()

    def serve(self, wfile):
        c, pm = self.client, self.manager
        try:
            wfile.write(b"retry: 3000\n\n")
            if self.replay:
                wfile.write(b"".join(
                    self._event_bytes(ev) for ev in self.replay))
            wfile.flush()
            while True:
                evs, state = c.take(timeout=pm.heartbeat)
                if evs:
                    # one syscall per wakeup, not per event: under load
                    # take() batches, so write count degrades gracefully
                    wfile.write(b"".join(
                        self._event_bytes(ev) for ev in evs))
                if state == "lost":
                    # terminal: this viewer overflowed (or resumed past
                    # the replay window) — it re-lists and reconnects
                    wfile.write(b"event: lost\ndata: {}\n\n")
                    wfile.flush()
                    return
                if state == "closed":
                    # graceful drain: tell the browser to back off the
                    # dying replica before the socket closes
                    wfile.write(b"retry: 30000\nevent: bye\ndata: {}\n\n")
                    wfile.flush()
                    return
                if not evs:
                    wfile.write(b": hb\n\n")
                wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            pm.unregister(c)


class ApiServer:
    def __init__(self, store: MemStore, sink: JobLogStore,
                 ks: Optional[Keyspace] = None, security=None, alarm=None,
                 auth_enabled: bool = True,
                 host: str = "127.0.0.1", port: int = 7079,
                 cache_enabled: Optional[bool] = None,
                 slo_engine=None, push_enabled: Optional[bool] = None,
                 sse_writer: Optional[str] = None):
        # auth_enabled=False replicates the reference's Web.Auth.Enabled
        # switch (web/base.go:98: every request passes as an implicit
        # admin; the UI skips login).  Unlike the reference — whose Go
        # zero value DISABLES auth unless configured — the rebuild's
        # default is enabled.
        self.auth_enabled = auth_enabled
        self._implicit_admin = Session(email=BOOTSTRAP_ADMIN,
                                       role=ROLE_ADMIN)
        self.store = store
        self.sink = sink
        self.ks = ks or Keyspace()
        self.security = security
        self.alarm = alarm
        self.sessions = SessionStore(store, self.ks)
        self.host, self.port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None
        # revision-vector response cache (web/cache.py): None = off —
        # today's recompute-per-poll behavior, exactly
        from .cache import ResponseCache, cache_default
        if cache_enabled is None:
            cache_enabled = cache_default()
        self.cache = ResponseCache() if cache_enabled else None
        self._bootstrap_admin()
        # result-store shard breakers page through the noticer this
        # process hosts: a browning-out logd shard writes a notice key
        # into the coordination store (store-shard breakers arm
        # themselves — they can write their own plane)
        arm = getattr(sink, "arm_breaker_notices", None)
        if arm is not None:
            try:
                arm(self.store, self.ks.prefix)
            except Exception as e:  # noqa: BLE001 — paging is optional
                log.warnf("breaker notice arming failed: %s", e)
        # SLO engine (web/slo.py): burn-rate evaluation + paging runs
        # in THIS process; None = engine hosted elsewhere (or off) —
        # the /v1/slo surfaces then serve specs without live burn rates
        self.slo_engine = slo_engine
        # live-push plane (web/push.py): one subscription per logd
        # shard feeding SSE fan-out and push-driven cache refresh.
        # CRONSUN_WEB_PUSH=off (or push_enabled=False) is the rollback:
        # no subscriptions, /v1/stream 503s, poll behavior unchanged.
        from .push import PushManager, push_default
        if push_enabled is None:
            push_enabled = push_default()
        self._push = None
        self._push_refreshers: OrderedDict = OrderedDict()
        self._push_ref_mu = threading.Lock()
        if push_enabled and hasattr(sink, "subscribe"):
            try:
                self._push = PushManager(
                    sink, on_change=self._push_refresh).start()
            except Exception as e:  # noqa: BLE001 — degrade to polling
                log.warnf("live push unavailable: %s", e)
                self._push = None
        # SSE writer mode: the epoll pool (web/sse_epoll.py) owns every
        # viewer socket by default; CRONSUN_SSE_WRITER=threads (or
        # sse_writer="threads") is the rollback to the PR 17
        # thread-per-connection writer — byte-identical on the wire,
        # pinned by tests/test_sse_epoll.py
        mode = (sse_writer or os.environ.get("CRONSUN_SSE_WRITER", "")
                or "epoll").strip().lower()
        self.sse_writer = "threads" if mode in ("threads", "thread") \
            else "epoll"
        self._sse_pool = None
        self._sse_adopted: set = set()
        self._sse_adopt_mu = threading.Lock()
        if self._push is not None and self.sse_writer == "epoll":
            from .sse_epoll import EpollSsePool
            self._sse_pool = EpollSsePool(
                self._push, on_close=self._sse_forget)
        self.routes = self._build_routes()

    # ---- SSE socket adoption (epoll writer) ------------------------------
    # The HTTP layer marks a streaming socket adopted BEFORE handing it
    # to the pool; socketserver's per-request teardown then skips it
    # (shutdown_request would otherwise send FIN under the pool).  The
    # marker is consumed by whichever side tears down first — the
    # request thread exiting or the pool closing the socket — and both
    # paths are safe against the other having already run because a
    # closed Python socket's fd is -1 (no fd-reuse hazard).

    def _sse_adopt(self, sock):
        with self._sse_adopt_mu:
            self._sse_adopted.add(sock)

    def _sse_forget(self, sock) -> bool:
        with self._sse_adopt_mu:
            if sock in self._sse_adopted:
                self._sse_adopted.discard(sock)
                return True
            return False

    # ---- bootstrap (web/authentication.go:20-52) -------------------------

    def _bootstrap_admin(self):
        if self.sink.get_account(BOOTSTRAP_ADMIN) is None:
            salt = next_id()
            acc = Account(email=BOOTSTRAP_ADMIN, salt=salt,
                          password=hash_password(BOOTSTRAP_PASSWORD, salt),
                          role=ROLE_ADMIN, unchangeable=True)
            self.sink.upsert_account(acc.email, acc.to_json())

    # ---- routing ---------------------------------------------------------

    def _build_routes(self):
        R = []

        def route(method, pattern, fn, auth=True, admin=False):
            R.append((method, re.compile("^" + pattern + "$"), fn, auth,
                      admin))

        route("GET", r"/v1/version", self.get_version, auth=False)
        route("GET", r"/v1/session", self.login, auth=False)
        # POST variant: credentials ride the JSON body, not the query
        # string, so they can't land in proxy/access logs (the GET route
        # stays for UI compatibility with the reference's login flow)
        route("POST", r"/v1/session", self.login, auth=False)
        route("GET", r"/v1/session/me", self.session_me)
        route("DELETE", r"/v1/session", self.logout)
        route("POST", r"/v1/user/setpwd", self.set_password)
        route("GET", r"/v1/admin/accounts", self.admin_list, admin=True)
        route("GET", r"/v1/admin/account/(?P<email>[^/]+)", self.admin_get,
              admin=True)
        route("PUT", r"/v1/admin/account", self.admin_add, admin=True)
        route("POST", r"/v1/admin/account", self.admin_update, admin=True)
        route("GET", r"/v1/jobs", self.job_list)
        route("GET", r"/v1/job/groups", self.job_groups)
        route("PUT", r"/v1/job", self.job_update)
        route("GET", r"/v1/job/executing", self.job_executing)
        route("POST", r"/v1/job/(?P<group>[^/]+)-(?P<id>[^/-]+)",
              self.job_change_status)
        route("GET", r"/v1/job/(?P<group>[^/]+)-(?P<id>[^/-]+)", self.job_get)
        route("DELETE", r"/v1/job/(?P<group>[^/]+)-(?P<id>[^/-]+)",
              self.job_delete)
        route("GET", r"/v1/dag/(?P<group>[^/]+)/runs", self.dag_runs)
        route("GET", r"/v1/dag/(?P<group>[^/]+)", self.dag_show)
        route("GET", r"/v1/job/(?P<group>[^/]+)-(?P<id>[^/-]+)/nodes",
              self.job_nodes)
        route("PUT", r"/v1/job/(?P<group>[^/]+)-(?P<id>[^/-]+)/execute",
              self.job_execute)
        route("GET", r"/v1/logs", self.log_list)
        # live event stream (SSE) — the poll loop's push replacement
        route("GET", r"/v1/stream", self.log_stream)
        route("GET", r"/v1/log/(?P<id>\d+)", self.log_detail)
        route("GET", r"/v1/stat/overall", self.stat_overall)
        route("GET", r"/v1/stat/days", self.stat_days)
        route("GET", r"/v1/nodes", self.node_list)
        route("GET", r"/v1/node/groups", self.group_list)
        route("GET", r"/v1/node/group/(?P<id>[^/]+)", self.group_get)
        route("PUT", r"/v1/node/group", self.group_update)
        route("DELETE", r"/v1/node/group/(?P<id>[^/]+)", self.group_delete)
        route("GET", r"/v1/tenants", self.tenant_list)
        route("PUT", r"/v1/tenant", self.tenant_set, admin=True)
        route("GET", r"/v1/tenant/(?P<id>[^/]+)", self.tenant_get)
        route("DELETE", r"/v1/tenant/(?P<id>[^/]+)", self.tenant_delete,
              admin=True)
        route("GET", r"/v1/sched", self.sched_status)
        # store replication plane: per-shard role/lag/epoch (repl/)
        route("GET", r"/v1/repl", self.repl_status)
        route("GET", r"/v1/info/overview", self.overview)
        route("GET", r"/v1/configurations", self.configurations)
        route("POST", r"/v1/checkpoint", self.checkpoint, admin=True)
        # trace plane: assembled waterfalls + slowest-trace summaries
        route("GET", r"/v1/trace/top", self.trace_top)
        route("GET", r"/v1/trace/(?P<job>[^/]+)/(?P<sec>\d+)",
              self.trace_show)
        # SLO engine: declarative specs + live burn rates
        route("GET", r"/v1/slos", self.slo_list)
        route("PUT", r"/v1/slo", self.slo_set, admin=True)
        route("DELETE", r"/v1/slo/(?P<name>[^/]+)", self.slo_delete,
              admin=True)
        route("GET", r"/v1/slo/status", self.slo_status)
        # liveness/readiness (unauthenticated: probes don't log in)
        route("GET", r"/healthz", self.healthz, auth=False)
        route("GET", r"/readyz", self.readyz, auth=False)
        # unauthenticated like /v1/version: Prometheus scrapers don't
        # hold sessions, and the surface carries only operational gauges
        route("GET", r"/v1/metrics", self.metrics, auth=False)
        return R

    # ---- handlers: auth --------------------------------------------------

    def get_version(self, ctx):
        return VERSION

    def login(self, ctx):
        body = ctx.json()
        if not isinstance(body, dict):
            raise HttpError(400, "body must be a JSON object")
        email = body.get("email") or ctx.q("email")
        password = body.get("password") or ctx.q("password")
        if (not body.get("email") and ctx.q("email")) or \
                (not body.get("password") and ctx.q("password")):
            # credentials in a query string land in proxy/access logs;
            # the GET route survives only for reference-UI compatibility
            log.warnf("deprecated query-string credentials on "
                      "/v1/session — use POST with a JSON body")
        doc = self.sink.get_account(email)
        if doc is None:
            raise HttpError(401, "invalid email or password")
        acc = Account.from_json(doc)
        if acc.status == 0 or not acc.check_password(password):
            raise HttpError(401, "invalid email or password")
        sid = self.sessions.create(acc.email, acc.role)
        ctx.set_cookie("sid", sid)
        return {"email": acc.email, "role": acc.role}

    def session_me(self, ctx):
        """Who am I — the UI restores its logged-in state across page
        reloads from this (the auth gate already resolved the session)."""
        return {"email": ctx.session.email, "role": ctx.session.role}

    def logout(self, ctx):
        if ctx.sid:
            self.sessions.destroy(ctx.sid)
        ctx.set_cookie("sid", "")
        return {}

    def set_password(self, ctx):
        body = ctx.json()
        old, new = body.get("password", ""), body.get("newPassword", "")
        if len(new) < 4:
            raise HttpError(400, "new password too short")
        doc = self.sink.get_account(ctx.session.email)
        acc = Account.from_json(doc)
        if not acc.check_password(old):
            raise HttpError(401, "wrong password")
        acc.salt = next_id()
        acc.password = hash_password(new, acc.salt)
        self.sink.upsert_account(acc.email, acc.to_json())
        return {}

    # ---- handlers: admin accounts ---------------------------------------

    @staticmethod
    def _pub(acc: Account) -> dict:
        return {"email": acc.email, "role": acc.role, "status": acc.status,
                "unchangeable": acc.unchangeable}

    def admin_list(self, ctx):
        return [self._pub(Account.from_json(d))
                for d in self.sink.list_accounts()]

    def admin_get(self, ctx):
        doc = self.sink.get_account(ctx.path_args["email"])
        if doc is None:
            raise HttpError(404, "no such account")
        return self._pub(Account.from_json(doc))

    def admin_add(self, ctx):
        body = ctx.json()
        email = (body.get("email") or "").strip().lower()
        password = body.get("password") or ""
        if "@" not in email or len(password) < 4:
            raise HttpError(400, "invalid email or password")
        if self.sink.get_account(email) is not None:
            raise HttpError(409, "account exists")
        salt = next_id()
        acc = Account(email=email, salt=salt,
                      password=hash_password(password, salt),
                      role=int(body.get("role", 2)),
                      status=int(body.get("status", 1)),
                      tenant=str(body.get("tenant", "") or "").strip())
        self.sink.upsert_account(acc.email, acc.to_json())
        return {}

    def admin_update(self, ctx):
        body = ctx.json()
        email = (body.get("email") or "").strip().lower()
        doc = self.sink.get_account(email)
        if doc is None:
            raise HttpError(404, "no such account")
        acc = Account.from_json(doc)
        if acc.unchangeable and ctx.session.email != acc.email:
            raise HttpError(403, "account is unchangeable")
        if "role" in body:
            acc.role = int(body["role"])
        if "status" in body:
            acc.status = int(body["status"])
        if "tenant" in body:
            acc.tenant = str(body["tenant"] or "").strip()
        if body.get("password"):
            acc.salt = next_id()
            acc.password = hash_password(body["password"], acc.salt)
        self.sink.upsert_account(acc.email, acc.to_json())
        self.sessions.destroy_email(email)   # force re-login on edit
        return {}

    # ---- handlers: jobs --------------------------------------------------

    def job_list(self, ctx):
        group = ctx.q("group")
        prefix = self.ks.cmd + (group + "/" if group else "")
        out = []
        latest, _ = self.sink.query_logs(latest=True, page_size=500)
        status = {}
        for l in latest:
            cur = status.setdefault(l.job_id, {"success": 0, "failed": 0})
            cur["success" if l.success else "failed"] += 1
        for kv in self._degraded_prefix(prefix):
            try:
                job = Job.from_json(kv.value)
            except (json.JSONDecodeError, TypeError):
                continue
            d = json.loads(job.to_json())
            d["latest_status"] = status.get(job.id)
            out.append(d)
        return out

    def job_groups(self, ctx):
        groups = set()
        for kv in self.store.get_prefix(self.ks.cmd):
            rest = kv.key[len(self.ks.cmd):]
            if "/" in rest:
                groups.add(rest.split("/", 1)[0])
        return sorted(groups)

    def _tenant_quota(self, tenant: str) -> Optional[TenantQuota]:
        if not tenant:
            return None
        kv = self.store.get(self.ks.tenant_quota_key(tenant))
        if kv is None:
            return None
        try:
            q = TenantQuota.from_json(kv.value)
            q.tenant = tenant
            q.validate()
            return q
        except (json.JSONDecodeError, TypeError, ValueError,
                ValidationError):
            return None

    def _account_tenant(self, ctx) -> str:
        """The session account's pinned tenant ("" = unpinned).  Admins
        are never pinned; with auth off every request is an implicit
        admin (reference Web.Auth.Enabled semantics)."""
        sess = ctx.session
        if not self.auth_enabled or sess is None \
                or sess.role == ROLE_ADMIN:
            return ""
        doc = self.sink.get_account(sess.email)
        if doc is None:
            return ""
        return Account.from_json(doc).tenant or ""

    def _guard_pinned(self, ctx, tenant: str):
        """Refuse a MUTATION of a job owned by another tenant (or the
        default tenant) from a tenant-pinned account — pinning must
        cover pause/delete/run-now/overwrite, not just the tenant
        field on create."""
        acc = self._account_tenant(ctx)
        if acc and (tenant or "") != acc:
            raise HttpError(
                403, f"account is pinned to tenant {acc!r}; cannot "
                     f"modify jobs of tenant "
                     f"{(tenant or 'default')!r}")

    @staticmethod
    def _doc_tenant(value: str) -> str:
        try:
            return json.loads(value).get("tenant") or ""
        except (json.JSONDecodeError, TypeError, AttributeError):
            return ""

    def job_update(self, ctx):
        body = ctx.json()
        old_group = (body.pop("oldGroup", "") or "").strip()
        job = Job.from_json(json.dumps(body))
        try:
            job.check()
            job.security_valid(self.security)
        except ValidationError as e:
            raise HttpError(400, str(e))
        # tenancy: a tenant-pinned account's jobs land in ITS tenant —
        # a mismatching explicit tenant is refused, not silently moved
        acc_tenant = self._account_tenant(ctx)
        if acc_tenant:
            if job.tenant and job.tenant != acc_tenant:
                raise HttpError(
                    403, f"account is pinned to tenant {acc_tenant!r}; "
                         f"cannot write jobs for {job.tenant!r}")
            job.tenant = acc_tenant
        # the document this PUT replaces (same id; possibly the old
        # group on a move): its (tenant, group) decides whether the
        # max_jobs gate sees a NEW job and which index marker to retire
        src_group = old_group if (old_group and old_group != job.group) \
            else job.group
        prev_kv = self.store.get(self.ks.job_key(src_group, job.id))
        prev = None
        if prev_kv is not None:
            prev = (self._doc_tenant(prev_kv.value), src_group)
            # overwriting another tenant's (or an untenanted) existing
            # job from a pinned account is a cross-tenant move — refuse
            self._guard_pinned(ctx, prev[0])
        dest = None
        if src_group != job.group:
            # a group move can ALSO overwrite a pre-existing job at
            # the DESTINATION id: guard it and retire its marker too,
            # or the clobbered tenant's index counts the ghost forever
            dest_kv = self.store.get(self.ks.job_key(job.group, job.id))
            if dest_kv is not None:
                dest = (self._doc_tenant(dest_kv.value), job.group)
                self._guard_pinned(ctx, dest[0])
        reserved = None
        if job.tenant:
            quota = self._tenant_quota(job.tenant)
            # a PUT that replaces a same-tenant document — at the
            # source OR the move destination — is not a new job; the
            # destination case also keeps the reservation key from
            # ALIASING the live marker (a rollback would delete it)
            replaces = (prev is not None and prev[0] == job.tenant) or \
                (dest is not None and dest[0] == job.tenant)
            if quota is not None and quota.max_jobs and not replaces:
                # reserve the index marker FIRST, then recount: two
                # racing creates both see each other's marker and the
                # recount refuses past the quota (worst case both
                # roll back one slot under — refusal is the safe
                # direction; a plain count-then-put would admit both)
                reserved = self.ks.tenant_job_key(job.tenant,
                                                  job.group, job.id)
                self.store.put(reserved, "1")
                n = self.store.count_prefix(
                    self.ks.tenant_jobs(job.tenant))
                if n > quota.max_jobs:
                    self.store.delete(reserved)
                    raise HttpError(
                        429, f"tenant {job.tenant!r} is at its "
                             f"max_jobs quota "
                             f"({n - 1}/{quota.max_jobs}); delete "
                             "jobs or raise the quota")
        try:
            if job.deps is not None:
                # DAG validation is group-scoped: every upstream must
                # exist in the group and the new edges must not close
                # a cycle — refused HERE, loudly, before the document
                # lands (the scheduler would otherwise hold the job
                # forever)
                self._validate_job_dag(job)
            if old_group and old_group != job.group:
                # a group move deletes the old-group document: same
                # dependents guard as job_delete, or the move silently
                # breaks downstream chains the delete path refuses to
                dep_map, _ids = self._group_dep_map(old_group)
                dependents = sorted(j for j, ups in dep_map.items()
                                    if job.id in ups and j != job.id)
                if dependents:
                    raise HttpError(
                        409, f"job {job.id!r} is an upstream of "
                             f"{', '.join(dependents)} in group "
                             f"{old_group!r} — moving it would break "
                             "their chains; update or delete the "
                             "dependents first")
                self.store.delete(self.ks.job_key(old_group, job.id))
            self.store.put(self.ks.job_key(job.group, job.id),
                           job.to_json())
        except BaseException:
            # a refusal after the reservation must not leak the
            # marker (it would count a job that never landed)
            if reserved is not None:
                self.store.delete(reserved)
            raise
        # per-tenant job index: retire the replaced document's marker
        # when its (tenant, group) moved, then assert the new one (the
        # markers make the max_jobs gate one count_prefix, not a scan)
        for old in (prev, dest):
            if old is not None and old[0] and \
                    (old[0] != job.tenant or old[1] != job.group):
                self.store.delete(
                    self.ks.tenant_job_key(old[0], old[1], job.id))
        if job.tenant:
            self.store.put(
                self.ks.tenant_job_key(job.tenant, job.group, job.id),
                "1")
        return {"id": job.id, "group": job.group}

    def _group_dep_map(self, group: str):
        """{job_id: [upstream ids]} + the id set for one group (the
        validate_dag inputs), read straight from the store."""
        prefix = self.ks.cmd + group + "/"
        dep_map, ids = {}, set()
        for kv in self.store.get_prefix(prefix):
            jid = kv.key[len(prefix):]
            ids.add(jid)
            try:
                doc = json.loads(kv.value)
            except (json.JSONDecodeError, TypeError):
                continue
            d = doc.get("deps")
            if isinstance(d, dict) and d.get("on"):
                dep_map[jid] = [str(u) for u in d["on"]]
        return dep_map, ids

    def _validate_job_dag(self, job: Job):
        dep_map, ids = self._group_dep_map(job.group)
        dep_map[job.id] = list(job.deps.on)
        ids.add(job.id)
        try:
            validate_dag(dep_map, ids, job.id)
        except ValidationError as e:
            raise HttpError(400, str(e))

    def _load_job(self, ctx) -> Job:
        group, job_id = ctx.path_args["group"], ctx.path_args["id"]
        kv = self.store.get(self.ks.job_key(group, job_id))
        if kv is None:
            raise HttpError(404, "no such job")
        job = Job.from_json(kv.value)
        job.group, job.id = group, job_id
        job._mod_rev = kv.mod_rev
        return job

    def job_get(self, ctx):
        return json.loads(self._load_job(ctx).to_json())

    def job_delete(self, ctx):
        group, job_id = ctx.path_args["group"], ctx.path_args["id"]
        # deleting an upstream leaves its dependents' dep columns BROKEN
        # (they hold forever): refuse unless the operator forces it
        dep_map, _ids = self._group_dep_map(group)
        dependents = sorted(j for j, ups in dep_map.items()
                            if job_id in ups and j != job_id)
        if dependents and ctx.q("force") != "true":
            raise HttpError(
                409, f"job {job_id!r} is an upstream of "
                     f"{', '.join(dependents)} — their chains would "
                     "hold forever; delete them first or pass "
                     "?force=true")
        kv = self.store.get(self.ks.job_key(group, job_id))
        if kv is None:
            raise HttpError(404, "no such job")
        tenant = self._doc_tenant(kv.value)
        self._guard_pinned(ctx, tenant)
        if not self.store.delete(self.ks.job_key(group, job_id)):
            raise HttpError(404, "no such job")
        if tenant:
            self.store.delete(
                self.ks.tenant_job_key(tenant, group, job_id))
        return {}

    def job_change_status(self, ctx):
        """Pause/resume via CAS (reference web/job.go:54-79)."""
        job = self._load_job(ctx)
        self._guard_pinned(ctx, job.tenant)
        body = ctx.json()
        job.pause = bool(body.get("pause"))
        if not self.store.put_if_mod_rev(
                self.ks.job_key(job.group, job.id), job.to_json(),
                job._mod_rev):
            raise HttpError(409, "job was modified concurrently, retry")
        return json.loads(job.to_json())

    def job_nodes(self, ctx):
        """include ∪ groups − exclude (reference web/job.go:222-257)."""
        job = self._load_job(ctx)
        nodes = set()
        for rule in job.rules:
            nodes.update(rule.nids)
            for gid in rule.gids:
                kv = self.store.get(self.ks.group_key(gid))
                if kv is not None:
                    nodes.update(Group.from_json(kv.value).node_ids)
            nodes.difference_update(rule.exclude_nids)
        return sorted(nodes)

    def job_execute(self, ctx):
        """Run-now (reference web/job.go:259-276 -> once.go:14-17)."""
        group, job_id = ctx.path_args["group"], ctx.path_args["id"]
        kv = self.store.get(self.ks.job_key(group, job_id))
        if kv is None:
            raise HttpError(404, "no such job")
        self._guard_pinned(ctx, self._doc_tenant(kv.value))
        node = ctx.q("node")
        self.store.put(self.ks.once_key(group, job_id), node)
        return {}

    # ---- workflow DAG views ---------------------------------------------

    def _dag_group_jobs(self, group: str):
        """Jobs of the group that participate in its DAG (dep-triggered
        jobs + their upstreams), plus the dep-less lookup set."""
        prefix = self.ks.cmd + group + "/"
        jobs = {}
        for kv in self.store.get_prefix(prefix):
            jid = kv.key[len(prefix):]
            try:
                job = Job.from_json(kv.value)
            except (json.JSONDecodeError, TypeError):
                continue
            job.group, job.id = group, jid
            jobs[jid] = job
        dag = {jid: j for jid, j in jobs.items() if j.deps is not None}
        involved = set(dag)
        for j in dag.values():
            involved.update(j.deps.on)
        return jobs, dag, involved

    def dag_show(self, ctx):
        """Dependency graph of one group: involved jobs in topological
        order (upstreams first), edges, and broken references."""
        group = ctx.path_args["group"]
        jobs, dag, involved = self._dag_group_jobs(group)
        missing = {}
        for jid, j in dag.items():
            gone = [u for u in j.deps.on if u not in jobs]
            if gone:
                missing[jid] = gone
        # Kahn topo over the involved subgraph (cycles can't exist for
        # validated saves; hand-written store content falls back to
        # sorted order for any leftover)
        indeg = {jid: 0 for jid in involved}
        downs = {jid: [] for jid in involved}
        for jid, j in dag.items():
            for u in j.deps.on:
                if u in indeg:
                    indeg[jid] += 1
                    downs[u].append(jid)
        ready = sorted(j for j, d in indeg.items() if d == 0)
        order = []
        while ready:
            cur = ready.pop(0)
            order.append(cur)
            for dn in sorted(downs[cur]):
                indeg[dn] -= 1
                if indeg[dn] == 0:
                    ready.append(dn)
        order += sorted(j for j in involved if j not in set(order))
        out_jobs = []
        for jid in order:
            j = jobs.get(jid)
            if j is None:
                continue            # missing upstream: listed in missing
            d = json.loads(j.to_json())
            out_jobs.append({"id": jid, "name": j.name, "pause": j.pause,
                             "kind": j.kind, "deps": d.get("deps")})
        edges = [[u, jid] for jid, j in sorted(dag.items())
                 for u in j.deps.on]
        return {"group": group, "jobs": out_jobs, "edges": edges,
                "missing": missing}

    def dag_runs(self, ctx):
        """Live chain state per DAG job: latest completed round (the
        dep/ completion key) and in-flight executions (proc registry)."""
        group = ctx.path_args["group"]
        jobs, dag, involved = self._dag_group_jobs(group)
        in_flight = {}
        pfx = self.ks.proc
        for kv in self.store.get_prefix(pfx):
            rest = kv.key[len(pfx):].split("/")
            if len(rest) != 4 or rest[1] != group:
                continue
            if rest[2] in involved:
                in_flight[rest[2]] = in_flight.get(rest[2], 0) + 1
        out = []
        for jid in sorted(involved):
            j = jobs.get(jid)
            row = {"id": jid,
                   "deps": (json.loads(j.to_json()).get("deps")
                            if j is not None else None),
                   "missing": j is None,
                   "in_flight": in_flight.get(jid, 0),
                   "last_epoch": None, "last_status": ""}
            kv = self.store.get(self.ks.dep_key(group, jid))
            if kv is not None:
                epoch, _, status = kv.value.partition("|")
                try:
                    row["last_epoch"] = int(float(epoch))
                    row["last_status"] = status or "ok"
                except ValueError:
                    pass
            out.append(row)
        return {"group": group, "jobs": out}

    def job_executing(self, ctx):
        """Scan of the proc registry (reference web/job.go:278-337)."""
        node_f, job_f = ctx.q("node"), ctx.q("jobId")
        out = []
        for kv in self.store.get_prefix(self.ks.proc):
            parts = kv.key[len(self.ks.proc):].split("/")
            if len(parts) != 4:
                continue
            node, group, job_id, pid = parts
            if node_f and node != node_f:
                continue
            if job_f and job_id != job_f:
                continue
            try:
                info = json.loads(kv.value)
            except json.JSONDecodeError:
                info = {}
            out.append({"node": node, "group": group, "jobId": job_id,
                        "pid": pid, "time": info.get("time")})
        return sorted(out, key=lambda d: (d["node"], d["jobId"]))

    # ---- handlers: logs --------------------------------------------------

    def _sink_revision(self):
        """The result store's change token: scalar max record id
        (unsharded) or the per-shard vector (sharded) — one cheap read
        instead of re-running the dashboard query."""
        rev = getattr(self.sink, "revision", None)
        if rev is None:
            return None
        try:
            return rev()
        except Exception:  # noqa: BLE001 — pre-revision server
            return None

    @staticmethod
    def _rev_str(rev) -> str:
        return ",".join(str(v) for v in rev) \
            if isinstance(rev, (list, tuple)) else str(rev)

    def _etag_guard(self, ctx, extra: str = ""):
        """Revision-keyed ETag for the read endpoints: repeated
        dashboard polls answer ``304 Not Modified`` in O(1) — one
        revision read, no query — whenever nothing was written since
        the poll that produced the cached body.  ``extra``
        discriminates endpoints sharing the same revision key (a
        stat_days body and a latest-view body must not satisfy each
        other's cache)."""
        rev = self._sink_revision()
        if rev is None:
            return
        etag = f'W/"{extra}{self._rev_str(rev)}"'
        if ctx.header("If-None-Match") == etag:
            raise NotModified(etag)
        ctx.out_headers["ETag"] = etag

    def _sink_shards(self) -> list:
        """The sink as a shard list — the real shard clients when
        sharded, [sink] otherwise, so the cached scatter path has ONE
        shape."""
        return getattr(self.sink, "shards", None) or [self.sink]

    def _scatter_pool(self):
        """Lazy fan-out pool for cached-scatter recomputes (sharded
        sinks only reach it with > 1 changed shard)."""
        pool = getattr(self, "_scatter_pool_obj", None)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor
            pool = ThreadPoolExecutor(max_workers=8,
                                      thread_name_prefix="web-scatter")
            self._scatter_pool_obj = pool
        return pool

    def _cached_scatter(self, ctx, key, extra: str, per_shard, merge,
                        direct):
        """Serve a read endpoint through the revision-vector response
        cache: 304 on a matching If-None-Match (today's ETag contract,
        byte-identical tags), the cached body when the vector is
        unchanged, and on a CHANGED vector recompute ONLY the shards
        whose entry moved — unchanged shards' cached partials feed
        ``merge`` unchanged.  ``per_shard(client, i)`` must return a
        merge-stable partial; ``merge(parts)`` the response body.

        With the cache off (or a sink without revision support) this
        degrades to the plain guard + ``direct()`` — the sink's OWN
        merged read (the sharded client fans concurrently on its
        pool), exactly today's bytes AND today's latency."""
        rev = self._sink_revision()
        if rev is None or self.cache is None:
            self._etag_guard(ctx, extra)
            return direct()
        etag = f'W/"{extra}{self._rev_str(rev)}"'
        if ctx.header("If-None-Match") == etag:
            self.cache.bump("etag_304_total")
            raise NotModified(etag)
        ctx.out_headers["ETag"] = etag
        revs = list(rev) if isinstance(rev, (list, tuple)) else [rev]
        ent = self.cache.lookup(key)
        if ent is not None and ent["revs"] == revs:
            self.cache.bump("body_hits_total")
            return ent["body"]
        shards = self._sink_shards()
        same_shape = (ent is not None and len(ent["revs"]) == len(revs)
                      == len(shards))
        parts: list = [None] * len(shards)
        recompute = []
        reused = 0
        for i, s in enumerate(shards):
            if same_shape and ent["revs"][i] == revs[i]:
                # reuse is sound: equal revision means no write landed
                # on this shard since its partial was computed, so the
                # partial is exactly what a fresh scatter would return
                parts[i] = ent["parts"][i]
                reused += 1
            else:
                recompute.append((i, s))
        if len(recompute) > 1:
            # recompute CONCURRENTLY — the uncached path fanned shard
            # RPCs through the sharded client's pool, and a serial loop
            # here would turn the poll into the SUM of shard latencies
            futs = [(i, self._scatter_pool().submit(per_shard, s, i))
                    for i, s in recompute]
            first_err = None
            for i, f in futs:
                try:
                    parts[i] = f.result()
                except BaseException as e:  # noqa: BLE001 — collected
                    if first_err is None:
                        first_err = e
            if first_err is not None:
                raise first_err
        elif recompute:
            i, s = recompute[0]
            parts[i] = per_shard(s, i)
        body = merge(parts)
        self.cache.store(key, revs, parts, body)
        if ent is None:
            self.cache.bump("misses_total")
        self.cache.bump("shard_reused_total", reused)
        self.cache.bump("shard_recomputed_total", len(shards) - reused)
        if self._push is not None and self._push.running:
            # remember how to rebuild this entry: the push refresher
            # recomputes the changed shard's partial when events land,
            # so the NEXT poll body-hits instead of scattering.  The
            # closures capture only request-static filter state (never
            # ctx), so replaying them off-request is sound.
            with self._push_ref_mu:
                self._push_refreshers[key] = (per_shard, merge)
                self._push_refreshers.move_to_end(key)
                while len(self._push_refreshers) > 64:
                    self._push_refreshers.popitem(last=False)
        return body

    def _push_refresh(self) -> bool:
        """Recompute registered cache entries' CHANGED shard partials
        from the push-maintained vector (debounced by the manager).
        Labels are read BEFORE the recompute (the cache's documented
        soundness direction: a label older than the data can only cause
        an extra recompute, never a stale hit).  Returns True when any
        entry was refreshed."""
        if self.cache is None or self._push is None:
            return False
        with self._push_ref_mu:
            items = list(self._push_refreshers.items())
        if not items:
            return False
        shards = self._sink_shards()
        vec = self._push.vector()
        if len(vec) != len(shards):
            return False
        did = False
        for key, (per_shard, merge) in items:
            ent = self.cache.lookup(key)
            if ent is None:          # evicted: stop refreshing it
                with self._push_ref_mu:
                    self._push_refreshers.pop(key, None)
                continue
            revs = list(vec)
            if ent["revs"] == revs or len(ent["revs"]) != len(revs):
                continue
            parts = list(ent["parts"])
            try:
                for i, s in enumerate(shards):
                    if ent["revs"][i] != revs[i]:
                        parts[i] = per_shard(s, i)
                body = merge(parts)
            except Exception:  # noqa: BLE001 — next poll recomputes
                continue
            self.cache.store(key, revs, parts, body)
            did = True
        return did

    def _tenant_scope(self, ctx):
        """Effective tenant filter for the log/stat views: the explicit
        ``tenant=`` query, FORCED server-side to the account's pinned
        tenant for tenant-pinned sessions (a pinned dashboard cannot
        read other tenants' history by omitting or spoofing the
        parameter).  Returns ``(tenant, job_ids)``; ``job_ids`` is None
        when unscoped, else the tenant's job ids from the
        ``tenant/<t>/job/`` index markers set_job maintains (possibly
        empty — the caller short-circuits to an empty view)."""
        tenant = ctx.q("tenant")
        acc = self._account_tenant(ctx)
        if acc:
            if tenant and tenant != acc:
                raise HttpError(
                    403, f"account is pinned to tenant {acc!r}; cannot "
                         f"read tenant {tenant!r}")
            tenant = acc
        if not tenant:
            return "", None
        # short-TTL memo of the tenant -> job-ids resolution: the
        # latest view is THE dashboard poll, and an uncached index
        # scan per poll would put an O(tenant jobs) prefix RPC in
        # front of the response cache it exists to protect.  2 s of
        # staleness matches the poll cadence; a removed/added job's
        # records follow within one memo window.
        import time as _time
        memo = getattr(self, "_tenant_ids_memo", None)
        if memo is None:
            memo = self._tenant_ids_memo = {}
        now = _time.monotonic()
        ent = memo.get(tenant)
        if ent is not None and ent[0] > now:
            return tenant, ent[1]
        pfx = self.ks.tenant_jobs(tenant)
        ids = set()
        for kv in self.store.get_prefix(pfx):
            rest = kv.key[len(pfx):]
            if "/" in rest:
                ids.add(rest.split("/", 1)[1])
        out = sorted(ids)
        if len(memo) > 4096:    # unbounded-tenant-name backstop
            memo.clear()
        memo[tenant] = (now + 2.0, out)
        return tenant, out

    @staticmethod
    def _scoped_ids(ctx, tids):
        """Intersect the request's explicit ids filter with a tenant
        scope; either side absent passes the other through."""
        job_ids = ctx.q("ids").split(",") if ctx.q("ids") else None
        if tids is None:
            return job_ids
        if job_ids is None:
            return list(tids)
        allowed = set(tids)
        return [j for j in job_ids if j in allowed]

    def log_list(self, ctx):
        tenant, tids = self._tenant_scope(ctx)
        latest = ctx.q("latest") in ("true", "1")
        if latest:
            # the latest view is THE dashboard poll: revision-keyed 304
            # (and the response cache's partial reuse) makes an idle
            # dashboard O(1) per poll and a busy one O(changed shards)
            return self._log_latest(ctx, tenant, tids)
        job_ids = self._scoped_ids(ctx, tids)
        if tids is not None and not job_ids:
            return {"total": 0, "list": []}
        nshards = getattr(self.sink, "nshards", 1)
        after_raw = ctx.q("afterId")
        after_id = None
        if after_raw:
            if after_raw == "tail":
                # cursor bootstrap: revision AND the current tail from
                # ONE sink-side snapshot.  Reading them in two steps
                # (the old path: revision only, tail implied) lets a
                # record land in between — included in the cursor yet
                # absent from the tail page, so the first follow poll
                # (id > cursor) skips it forever.
                tsnap = getattr(self.sink, "tail_snapshot", None)
                rev = recs = None
                if tsnap is not None:
                    try:
                        rev, recs = tsnap(ctx.q_int("pageSize", 0) or 0)
                    except Exception:  # noqa: BLE001 — pre-snapshot server
                        rev = recs = None
                if rev is None:
                    rev = self._sink_revision()
                    recs = []
                if rev is None:
                    raise HttpError(400, "sink has no revision support")
                if tids is not None:
                    # tenant scope is a security boundary: the tail
                    # bootstrap page must not leak foreign records
                    allowed = set(tids)
                    recs = [r for r in recs if r.job_id in allowed]
                return {"total": -1,
                        "list": [self._log_dict(r) for r in recs],
                        "cursor": self._rev_str(rev)}
            try:
                if "," in after_raw:
                    after_id = [int(v) for v in after_raw.split(",")]
                else:
                    after_id = int(after_raw)
            except ValueError:
                raise HttpError(
                    400, f"bad integer for 'afterId': {after_raw!r}")
        try:
            recs, total = self.sink.query_logs(
                node=ctx.q("node") or None,
                job_ids=job_ids,
                name_like=ctx.q("names") or None,
                begin=ctx.q_float("begin"),
                end=ctx.q_float("end"),
                failed_only=ctx.q("failedOnly") in ("true", "1"),
                latest=latest,
                page=ctx.q_int("page", 1),
                page_size=ctx.q_int("pageSize", 50),
                # cursor mode for pollers: id > afterId (scalar, or the
                # per-shard vector a sharded sink's poller carries)
                after_id=after_id)
        except (ValueError, TypeError) as e:
            # a scalar cursor against a sharded sink, a wrong-length
            # vector, or a vector against an UNSHARDED sink (a stale
            # poller after a topology change — int(list) is the
            # TypeError) is a client error, not a 500
            raise HttpError(400, str(e))
        out = {"total": total, "list": [self._log_dict(r) for r in recs]}
        if after_id is not None:
            # the poller's next cursor: per delivered record (encoded
            # ids carry the shard), shards that delivered nothing keep
            # their entry
            vec = after_id if isinstance(after_id, list) else \
                ([0] * nshards if nshards > 1 else None)
            if vec is not None:
                from ..logsink.sharded import advance_cursor
                out["cursor"] = self._rev_str(
                    advance_cursor(vec, recs, nshards))
            else:
                nxt = max([after_id] + [r.id for r in recs
                                        if r.id is not None])
                out["cursor"] = str(nxt)
        return out

    def _log_latest(self, ctx, tenant: str = "", tids=None):
        """The latest view through the response cache: each shard's
        partial is its filtered top rows (exactly the sharded client's
        scatter fetch), the merge is the documented (begin_ts DESC,
        job_id, node) order — byte-identical to the direct
        ``sink.query_logs(latest=True, ...)`` path, pinned by test.
        A tenant scope narrows the job-ids filter server-side (and
        keys the cache, so scoped and unscoped polls never share a
        body)."""
        from ..logsink.sharded import (fetch_top, log_shard_index,
                                       merge_latest_parts)
        page = max(1, min(ctx.q_int("page", 1), 1 << 40))
        page_size = max(1, min(ctx.q_int("pageSize", 50), 500))
        job_ids = self._scoped_ids(ctx, tids)
        if tids is not None and not job_ids:
            return {"total": 0, "list": []}
        kw = dict(node=ctx.q("node") or None,
                  job_ids=job_ids,
                  name_like=ctx.q("names") or None,
                  begin=ctx.q_float("begin"),
                  end=ctx.q_float("end"),
                  failed_only=ctx.q("failedOnly") in ("true", "1"),
                  latest=True)
        need = page * page_size
        # the tenant scope keys the cache by its RESOLVED id set, not
        # the name: membership changes (job moved out of the tenant)
        # must change the key — the shard revisions only move on sink
        # writes, and a name-only key would keep serving the removed
        # job's cached records across the boundary
        key = ("latest", ctx.q("node"), ctx.q("ids"), ctx.q("names"),
               ctx.q("begin"), ctx.q("end"), ctx.q("failedOnly"),
               page, page_size, tenant,
               tuple(job_ids) if tids is not None else None)
        # a job-filtered poll touches only the filter's shards — the
        # sharded client's routing win, kept through the cache: pruned
        # shards contribute a constant empty partial without an RPC
        nshards = getattr(self.sink, "nshards", 1)
        sids = ({log_shard_index(j, nshards) for j in job_ids}
                if job_ids and nshards > 1 else None)

        def per_shard(s, i):
            if sids is not None and i not in sids:
                return [], 0
            return fetch_top(s, kw, need)

        def merge(parts):
            rows, total = merge_latest_parts(parts, page, page_size)
            return {"total": total,
                    "list": [self._log_dict(r) for r in rows]}

        def direct():
            rows, total = self.sink.query_logs(page=page,
                                               page_size=page_size, **kw)
            return {"total": total,
                    "list": [self._log_dict(r) for r in rows]}
        return self._cached_scatter(ctx, key, "logs:", per_shard, merge,
                                    direct)

    @staticmethod
    def _log_dict(r) -> dict:
        return {"id": r.id, "jobId": r.job_id, "jobGroup": r.job_group,
                "name": r.name, "node": r.node, "user": r.user,
                "command": r.command, "output": r.output,
                "success": r.success, "beginTime": r.begin_ts,
                "endTime": r.end_ts}

    def log_stream(self, ctx):
        """``GET /v1/stream`` — live SSE feed of new-record summaries,
        filtered SERVER-side (tenant pinning is forced exactly like the
        list endpoints: a pinned account cannot widen its stream by
        omitting or spoofing ``tenant=``).  ``Last-Event-ID`` (or
        ``cursor=``) resumes from a prior cursor vector through the
        PR 7 cursor query — exactly-once across the reconnect.  503
        when push is off/unavailable: clients fall back to polling."""
        pm = self._push
        if pm is None or not pm.running:
            raise HttpError(
                503, "live push is disabled on this server "
                     "(CRONSUN_WEB_PUSH=off or no subscribe support)")
        _tenant, tids = self._tenant_scope(ctx)
        job_ids = self._scoped_ids(ctx, tids)
        filters = {
            # the tenant scope is a security boundary; the ids filter a
            # convenience — both resolve to job-id sets evaluated per
            # event.  frozenset(()) (empty tenant) matches nothing.
            "tenant_ids": frozenset(tids) if tids is not None else None,
            "job_ids": frozenset(job_ids) if job_ids is not None
            else None,
            "node": ctx.q("node") or None,
            "failed_only": ctx.q("failedOnly") in ("true", "1"),
        }
        cursor_raw = ctx.header("Last-Event-ID") or ctx.q("cursor")
        client = pm.register(filters)
        replay: list = []
        if cursor_raw:
            try:
                vec = [int(v) for v in cursor_raw.split(",")]
            except ValueError:
                pm.unregister(client)
                raise HttpError(400, f"bad cursor {cursor_raw!r}")
            if len(vec) != pm.nshards:
                pm.unregister(client)
                raise HttpError(
                    400, f"cursor has {len(vec)} entries; this sink "
                         f"has {pm.nshards} shard(s)")
            try:
                replay = pm.replay(client, vec)
            except (ValueError, TypeError) as e:
                pm.unregister(client)
                raise HttpError(400, str(e))
            client.vec = list(vec) if pm.nshards > 1 else [vec[0]]
        return SseStream(pm, client, replay)

    def log_detail(self, ctx):
        rec = self.sink.get_log(int(ctx.path_args["id"]))
        if rec is None:
            raise HttpError(404, "no such log")
        # the tenant boundary covers the detail endpoint too: ids are
        # sequential, so without this a pinned account could enumerate
        # every tenant's command/output history around the list
        # filters.  404, not 403 — existence is part of the secret.
        _tenant, tids = self._tenant_scope(ctx)
        if tids is not None and rec.job_id not in set(tids):
            raise HttpError(404, "no such log")
        return self._log_dict(rec)

    # ---- handlers: stats (revision-keyed, 304 on unchanged) -------------

    def stat_overall(self, ctx):
        from ..logsink.sharded import ShardedJobLogStore
        tenant, tids = self._tenant_scope(ctx)
        if tids is not None:
            return self._tenant_stat_overall(tids)
        return self._cached_scatter(
            ctx, ("stat_overall",), "so:",
            lambda s, _i: s.stat_overall(),
            ShardedJobLogStore._sum_stats,
            self.sink.stat_overall)

    def _tenant_stat_overall(self, tids) -> dict:
        """Tenant-scoped overall stats, computed from the filtered
        record counts (the sink's aggregate tables are fleet-wide).
        Memoized a few seconds like _tenant_stat_days — the counts
        bypass the revision-keyed response cache and a pinned
        dashboard polls this every refresh."""
        if not tids:
            return {"total": 0, "successed": 0, "failed": 0}
        import time as _time
        memo = getattr(self, "_tenant_stat_memo", None)
        if memo is None:
            memo = self._tenant_stat_memo = {}
        mkey = ("overall", tuple(tids))
        now = _time.monotonic()
        ent = memo.get(mkey)
        if ent is not None and ent[0] > now:
            return ent[1]
        _r, total = self.sink.query_logs(job_ids=tids, page=1,
                                         page_size=1)
        _r, failed = self.sink.query_logs(job_ids=tids, failed_only=True,
                                          page=1, page_size=1)
        total = max(0, total)
        failed = max(0, failed)
        out = {"total": total, "successed": max(0, total - failed),
               "failed": failed}
        if len(memo) > 1024:
            memo.clear()
        memo[mkey] = (now + 5.0, out)
        return out

    def stat_days(self, ctx):
        from ..logsink.sharded import merge_stat_days
        tenant, tids = self._tenant_scope(ctx)
        n = ctx.q_int("days", 7)
        if tids is not None:
            if (n or 0) > 62:
                # the scoped path counts per day (no aggregate table):
                # refuse loudly rather than silently truncating a
                # quarterly dashboard to 62 days
                raise HttpError(
                    400, "tenant-scoped stat/days supports at most 62 "
                         "days")
            return self._tenant_stat_days(tids, max(0, n or 0))
        days = max(0, min(n or 0, 3660))
        return self._cached_scatter(
            ctx, ("stat_days", days), f"sd{n}:",
            lambda s, _i: s.stat_days(days),
            lambda parts: merge_stat_days(parts, days),
            lambda: self.sink.stat_days(days))

    def _tenant_stat_days(self, tids, n_days: int) -> list:
        """Tenant-scoped per-day stats over UTC day windows (clamped to
        62 days: up to two filtered counts per day).  Days with no
        records are omitted, matching the fleet-wide view's shape.
        Memoized for a few seconds per (tenant ids, days): the per-day
        counts bypass the revision-keyed response cache, and a pinned
        dashboard must not pay ~2·days count scans per poll."""
        import datetime as _dt
        import time as _time
        out = []
        if not tids:
            return out
        memo = getattr(self, "_tenant_stat_memo", None)
        if memo is None:
            memo = self._tenant_stat_memo = {}
        mkey = (tuple(tids), n_days)
        now = _time.monotonic()
        ent = memo.get(mkey)
        if ent is not None and ent[0] > now:
            return ent[1]
        today = _dt.datetime.now(_dt.timezone.utc).replace(
            hour=0, minute=0, second=0, microsecond=0)
        for i in range(n_days):
            day0 = today - _dt.timedelta(days=i)
            b, e = day0.timestamp(), day0.timestamp() + 86399.999
            _r, total = self.sink.query_logs(job_ids=tids, begin=b,
                                             end=e, page=1, page_size=1)
            if total <= 0:
                continue
            _r, failed = self.sink.query_logs(job_ids=tids, begin=b,
                                              end=e, failed_only=True,
                                              page=1, page_size=1)
            failed = max(0, failed)
            out.append({"day": day0.strftime("%Y-%m-%d"),
                        "total": total,
                        "successed": max(0, total - failed),
                        "failed": failed})
        if len(memo) > 1024:
            memo.clear()
        memo[mkey] = (now + 5.0, out)
        return out

    # ---- handlers: nodes + groups ---------------------------------------

    def _degraded_prefix(self, prefix: str):
        """Dashboard prefix scan: against a sharded store with its
        breaker armed, a browned-out shard's keys are served ABSENT
        (counted loudly as shard_degraded) instead of stalling or
        erroring the whole page.  Only for pure read views — never for
        paths that interpret a missing key as a deletion."""
        fn = getattr(self.store, "get_prefix_degraded", None)
        return fn(prefix) if fn is not None else \
            self.store.get_prefix(prefix)

    def _degraded_count(self, prefix: str) -> int:
        fn = getattr(self.store, "count_prefix_degraded", None)
        return fn(prefix) if fn is not None else \
            self.store.count_prefix(prefix)

    def node_list(self, ctx):
        """Result-store mirror ⋈ live keys (reference web/node.go:141-165).
        STRICT read: a missing liveness key renders as "disconnected" —
        a state, exactly what the degraded helper's contract forbids
        serving partially (a browned-out shard would paint its healthy
        nodes down)."""
        live = {kv.key[len(self.ks.node):]
                for kv in self.store.get_prefix(self.ks.node)}
        out = []
        for doc in self.sink.get_nodes():
            doc["connected"] = doc.get("id") in live
            out.append(doc)
        return out

    def group_list(self, ctx):
        return [json.loads(kv.value)
                for kv in self._degraded_prefix(self.ks.group)]

    def group_get(self, ctx):
        kv = self.store.get(self.ks.group_key(ctx.path_args["id"]))
        if kv is None:
            raise HttpError(404, "no such group")
        return json.loads(kv.value)

    def group_update(self, ctx):
        body = ctx.json()
        g = Group(id=body.get("id", ""), name=body.get("name", ""),
                  node_ids=list(body.get("nids") or []))
        try:
            g.check()
        except ValidationError as e:
            raise HttpError(400, str(e))
        self.store.put(self.ks.group_key(g.id), g.to_json())
        return {"id": g.id}

    def group_delete(self, ctx):
        """Delete + scrub the gid from every job's rules via CAS
        (reference web/node.go:78-139)."""
        gid = ctx.path_args["id"]
        if not self.store.delete(self.ks.group_key(gid)):
            raise HttpError(404, "no such group")
        for kv in self.store.get_prefix(self.ks.cmd):
            try:
                job = Job.from_json(kv.value)
            except (json.JSONDecodeError, TypeError):
                continue
            dirty = False
            for rule in job.rules:
                if gid in rule.gids:
                    rule.gids.remove(gid)
                    dirty = True
            if dirty:
                self.store.put_if_mod_rev(kv.key, job.to_json(), kv.mod_rev)
        return {}

    # ---- handlers: tenants ----------------------------------------------

    def _tenant_live_stats(self, tenant: str) -> dict:
        """Aggregate the schedulers' leased per-tenant snapshots for
        one tenant (counters sum across instances; gauges take the
        max — a standby's zeros must not mask the leader's numbers)."""
        agg: dict = {}
        for kv in self._degraded_prefix(self.ks.metrics + "tenant/"):
            try:
                snap = json.loads(kv.value)
            except json.JSONDecodeError:
                continue
            ent = snap.get(tenant)
            if not isinstance(ent, dict):
                continue
            for k, v in ent.items():
                if not isinstance(v, (int, float)):
                    continue
                if k.endswith(("_fires", "_total")):
                    agg[k] = agg.get(k, 0) + v
                else:
                    agg[k] = max(agg.get(k, 0), v)
        return agg

    def tenant_list(self, ctx):
        # ONE prefix listing serves quotas, names AND the per-tenant
        # job counts (the /job/ index markers are right there — a
        # count_prefix per tenant would be N+1 fan-out RPCs)
        quotas: dict = {}
        counts: dict = {}
        pfx = self.ks.tenant
        for kv in self._degraded_prefix(pfx):
            rest = kv.key[len(pfx):]
            name, _, tail = rest.partition("/")
            if not name:
                continue
            if tail == "quota":
                try:
                    q = TenantQuota.from_json(kv.value)
                    q.tenant = name
                    quotas[name] = q
                except (json.JSONDecodeError, TypeError, ValueError):
                    continue
            elif tail.startswith("job/"):
                counts[name] = counts.get(name, 0) + 1
        out = []
        for name in sorted(set(quotas) | set(counts)):
            q = quotas.get(name)
            out.append({"tenant": name, "jobs": counts.get(name, 0),
                        "quota": q.to_dict() if q else None})
        return out

    def tenant_get(self, ctx):
        name = ctx.path_args["id"]
        q = self._tenant_quota(name)    # one get, not a prefix scan
        jobs = self._degraded_count(self.ks.tenant_jobs(name))
        if q is None and not jobs:
            raise HttpError(404, "no such tenant")
        return {"tenant": name, "jobs": jobs,
                "quota": q.to_dict() if q else None,
                "live": self._tenant_live_stats(name)}

    def tenant_set(self, ctx):
        body = ctx.json()
        q = TenantQuota(
            tenant=str(body.get("tenant", "")),
            max_jobs=int(body.get("max_jobs", 0) or 0),
            rate=float(body.get("rate", 0) or 0),
            burst=float(body.get("burst", 0) or 0),
            max_running=int(body.get("max_running", 0) or 0),
            weight=float(body.get("weight", 1.0) or 1.0))
        try:
            q.validate()
        except ValidationError as e:
            raise HttpError(400, str(e))
        self.store.put(self.ks.tenant_quota_key(q.tenant), q.to_json())
        return q.to_dict()

    def tenant_delete(self, ctx):
        name = ctx.path_args["id"]
        if not self.store.delete(self.ks.tenant_quota_key(name)):
            raise HttpError(404, "no such tenant quota")
        return {}

    # ---- handlers: info --------------------------------------------------

    def overview(self, ctx):
        live = self._degraded_count(self.ks.node)
        # planner health straight from the leased scheduler snapshots
        # (same source as /v1/metrics), keyed by instance
        scheds = {}
        for kv in self._degraded_prefix(self.ks.metrics + "sched/"):
            try:
                scheds[kv.key.rsplit("/", 1)[1]] = json.loads(kv.value)
            except json.JSONDecodeError:
                pass
        return {
            "totalJobs": self._degraded_count(self.ks.cmd),
            "jobExecuted": self.sink.stat_overall(),
            "jobExecutedDaily": self.sink.stat_days(7),
            "nodeCount": len(self.sink.get_nodes()),
            "nodeAlived": live,
            "schedulers": scheds,
        }

    def configurations(self, ctx):
        sec = self.security
        return {
            "security": {
                "open": bool(sec and sec.open),
                "users": list(sec.users) if sec else [],
                "exts": list(sec.exts) if sec else [],
            },
            "alarm": bool(self.alarm),
        }

    # ---- handlers: checkpoint plane --------------------------------------

    def checkpoint(self, ctx):
        """Operator checkpoint trigger (``cronsun-ctl checkpoint``):
        snapshot the coordination store's WAL (when the backing server
        persists) and ask every scheduler to save its state checkpoint
        — they watch the ckpt prefix and ack under ``ckpt/done/<id>``;
        save health is also visible as ``cronsun_sched_checkpoint_*``
        gauges at ``/v1/metrics``."""
        import time as _time
        out = {}
        snap = getattr(self.store, "snapshot", None)
        if snap is None:
            out["store_snapshot"] = "unsupported by this store client"
        else:
            try:
                out["store_snapshot_rev"] = snap()
            except Exception as e:  # noqa: BLE001 — store without a WAL
                out["store_snapshot"] = f"unavailable: {e}"
        self.store.put(self.ks.ckpt_req, str(int(_time.time() * 1000)))
        out["scheduler"] = ("checkpoint requested; acks land under "
                            f"{self.ks.ckpt}done/, save health at "
                            "/v1/metrics (cronsun_sched_checkpoint_*)")
        return out

    # ---- handlers: trace plane ------------------------------------------

    def trace_show(self, ctx):
        """Assembled waterfall for one (job, scheduled second): per
        executing node, the six stage durations (sched / publish /
        claim / queue / run / record) from the stored span stamps."""
        job = ctx.path_args["job"]
        sec = int(ctx.path_args["sec"])
        tg = getattr(self.sink, "trace_get", None)
        if tg is None:
            raise HttpError(501, "result store lacks the trace plane")
        try:
            spans = tg(job, sec)
        except Exception as e:  # noqa: BLE001 — degraded sink
            raise HttpError(503, f"trace read failed: {e}")
        wf = _trace.assemble(job, sec, spans)
        if wf is None:
            raise HttpError(
                404, "no trace recorded for this (job, second): not "
                     "sampled (trace_sample_shift), not yet flushed, "
                     "or aged out of the ring and spill")
        return wf

    def trace_top(self, ctx):
        """Slowest recent traces, optionally by one stage
        (?stage=claim&n=10) — summaries straight off the logd rings."""
        n = ctx.q_int("n", 10)
        stage = ctx.q("stage")
        if stage and stage not in _trace.STAGES:
            raise HttpError(400, f"unknown stage {stage!r} (one of "
                                 f"{', '.join(_trace.STAGES)})")
        tt = getattr(self.sink, "trace_top", None)
        if tt is None:
            raise HttpError(501, "result store lacks the trace plane")
        ents = tt(max(64, n * 4))

        def key(ent):
            if not stage:
                return ent.get("total_ms", 0.0)
            return max((nd.get("stages", {}).get(stage, 0.0)
                        for nd in ent.get("nodes", [])), default=0.0)
        ents.sort(key=key, reverse=True)
        return {"stage": stage or "total", "traces": ents[:max(1, n)]}

    # ---- handlers: SLO engine -------------------------------------------

    def slo_list(self, ctx):
        out = []
        for kv in self._degraded_prefix(self.ks.slo):
            try:
                out.append(dataclasses.asdict(SloSpec.from_json(kv.value)))
            except (json.JSONDecodeError, TypeError):
                continue
        return out

    def slo_set(self, ctx):
        body = ctx.json()
        try:
            # no `or`-defaulting: target=0 must reach validate() and
            # 400 ("target must be in (0, 1)"), not silently become
            # the default; a non-numeric value is a 400 too, like
            # every sibling route, not an unexplained 500
            spec = SloSpec(
                name=str(body.get("name", "")),
                scope=str(body.get("scope", "")),
                target=float(body.get("target", 0.999)),
                latency_ms=float(body.get("latency_ms", 0)))
            spec.validate()
        except (ValidationError, TypeError, ValueError) as e:
            raise HttpError(400, str(e))
        self.store.put(self.ks.slo_key(spec.name), spec.to_json())
        return dataclasses.asdict(spec)

    def slo_delete(self, ctx):
        name = ctx.path_args["name"]
        if not self.store.delete(self.ks.slo_key(name)):
            raise HttpError(404, "no such slo")
        return {}

    def slo_status(self, ctx):
        """Current burn rates + alert states (the `cronsun-ctl slo
        show` surface)."""
        if self.slo_engine is None:
            return {"engine": "off", "slos": {}, "stats": {}}
        snap = self.slo_engine.snapshot()
        snap["engine"] = "on"
        return snap

    # ---- handlers: health ------------------------------------------------

    def healthz(self, ctx):
        return {"ok": True}

    def readyz(self, ctx):
        """Readiness: the coordination store and result store answer,
        and no shard breaker is OPEN.  503 with the failing check named
        otherwise (the shared health contract — see
        cronsun_tpu/health.py for the TCP servers' twin)."""
        checks = {}

        def check(name, fn):
            try:
                ok, detail = fn()
            except Exception as e:  # noqa: BLE001
                ok, detail = False, str(e)
            checks[name] = {"ok": bool(ok), "detail": detail}

        def store_ok():
            self.store.get(self.ks.hwm)   # raises when unreachable
            return True, ""

        def sink_ok():
            return True, f"revision {self.sink.revision()}"

        def sched_partitions_ok():
            """With a pinned partition map, readiness demands a live
            leader PER PARTITION (leased sched snapshots expire with
            dead processes, so a leaderless partition shows up within
            one lease ttl).  Unpartitioned fleets skip the check."""
            p, malformed, _snaps, leaderless = self._sched_fleet_view()
            if malformed:
                return False, "malformed partmap"
            if p is None:
                return True, "unpartitioned"
            if p <= 1:
                return True, "p=1"
            if leaderless:
                return False, f"{p} partitions, leaderless: {leaderless}"
            return True, f"all {p} partitions led"

        check("store", store_ok)
        check("logsink", sink_ok)
        if self._push is not None:
            # a dead shard subscription is a NAMED failing check, not
            # silent staleness: the stream (and push-refreshed cache)
            # for that shard is stale until the loop resubscribes, and
            # the operator's rollback is CRONSUN_WEB_PUSH=off
            for si, (ok_, detail) in enumerate(self._push.health()):
                checks[f"push_shard_{si}"] = {"ok": bool(ok_),
                                              "detail": detail}
        # INFORMATIONAL: a leaderless scheduler partition is surfaced
        # here (and on /v1/sched, metrics, and the schedulers' own
        # health ports) but must NOT 503 the web tier — everything
        # this server serves still works, and failing readiness would
        # drain every healthy web replica from the load balancer over
        # a routine partition failover
        check("sched_partitions", sched_partitions_ok)
        checks["sched_partitions"]["informational"] = True
        for label, backend in (("store", self.store),
                               ("logsink", self.sink)):
            bs = getattr(backend, "breaker_snapshot", None)
            if bs is None:
                continue
            snaps = bs() or []
            opened = [s["shard"] for s in snaps
                      if s.get("state") == "open"]
            checks[f"{label}_breakers"] = {
                "ok": not opened,
                "detail": f"open shards: {opened}" if opened else ""}
        ok = all(c["ok"] for c in checks.values()
                 if not c.get("informational"))
        if not ok:
            ctx.out_status = 503
        return {"ok": ok, "checks": checks}

    # ---- handlers: scheduler plane status -------------------------------

    def _sched_fleet_view(self):
        """Shared source for readyz's partition check and /v1/sched:
        the pinned topology (None = no pin, ``malformed`` flagged
        separately) plus every live scheduler's leased snapshot and
        the leaderless-partition set — ONE implementation so the two
        surfaces cannot drift."""
        partitions = None
        malformed = False
        kv = self.store.get(self.ks.partmap)
        if kv is not None:
            try:
                doc = json.loads(kv.value)
                if not isinstance(doc, dict):
                    raise ValueError("partmap is not an object")
                partitions = int(doc.get("p", 1))
            except (json.JSONDecodeError, TypeError, ValueError):
                malformed = True
        snaps = []
        for mkv in self.store.get_prefix(self.ks.metrics + "sched/"):
            instance = mkv.key[len(self.ks.metrics) + len("sched/"):]
            try:
                snap = json.loads(mkv.value)
            except json.JSONDecodeError:
                continue
            snaps.append((instance, snap))
        leaderless = []
        if partitions and partitions > 1:
            led = {int(s["partition"]) for _i, s in snaps
                   if s.get("is_leader")
                   and isinstance(s.get("partition"), (int, float))}
            leaderless = [i for i in range(partitions) if i not in led]
        return partitions, malformed, snaps, leaderless

    def sched_status(self, ctx):
        """Per-partition scheduler fleet view (the ``cronsun-ctl sched
        status`` surface): the pinned partition topology plus every
        live scheduler's leased snapshot — leaders AND warm standbys —
        so a stalled or leaderless partition is one call away."""
        partitions, _malformed, snaps, leaderless = \
            self._sched_fleet_view()
        insts = []
        for instance, snap in snaps:
            insts.append({
                "instance": instance,
                "partition": snap.get("partition"),
                "is_leader": int(snap.get("is_leader", 0) or 0),
                "steps_total": snap.get("steps_total", 0),
                "dispatches_total": snap.get("dispatches_total", 0),
                "sched_step_p99_ms": snap.get("sched_step_p99_ms", 0),
                "jobs": snap.get("jobs", 0),
                "watch_losses_total": snap.get("watch_losses_total", 0),
                "lease_resigns_total":
                    snap.get("lease_resigns_total", 0),
                "skipped_seconds_total":
                    snap.get("skipped_seconds_total", 0),
                "checkpoint_restored":
                    snap.get("checkpoint_restored", 0),
                "acct_partitions_seen":
                    snap.get("acct_partitions_seen"),
            })
        insts.sort(key=lambda d: (d["partition"] if d["partition"]
                                  is not None else -1, d["instance"]))
        return {"partitions": partitions, "instances": insts,
                "leaderless": leaderless}

    def repl_status(self, ctx):
        """Per-shard store replication view (the ``cronsun-ctl repl
        status`` surface): every replica's role, applied revision,
        lag, and fencing epoch — who leads each shard, and how far
        behind each follower reads, one call away."""
        from ..repl import fleet_repl_status
        return {"shards": fleet_repl_status(self.store)}

    # ---- handlers: metrics ----------------------------------------------

    def metrics(self, ctx):
        """Prometheus text surface for the whole fleet: every component
        publishes a leased JSON snapshot under /metrics/<component>/<id>
        (cronsun_tpu.metrics.MetricsPublisher), so "is the planner
        keeping up" is one scrape away from any web server — dead
        publishers' snapshots expire with their lease."""
        lines = ["# HELP cronsun_web_up this web server is serving",
                 "# TYPE cronsun_web_up gauge",
                 "cronsun_web_up 1"]
        if self.cache is not None:
            # response-cache effectiveness (this web server's own):
            # 304s, whole-body hits, and the per-shard partial
            # reuse/recompute split behind CHANGED polls
            for field, val in sorted(self.cache.snapshot().items()):
                name = f"cronsun_web_cache_{field}"
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {val}")
        if self._push is not None:
            # live-push observability: viewer count, fan-out volume,
            # slow-consumer drops, resumes (this web server's own) —
            # plus the epoll writer pool's loop lag, ring evictions,
            # and write-queue depth when that writer is active
            sse_stats = dict(self._push.stats())
            per_loop = None
            if self._sse_pool is not None:
                pool_stats = self._sse_pool.stats()
                per_loop = pool_stats.pop("loop_connections", None)
                sse_stats.update(pool_stats)
            for field, val in sorted(sse_stats.items()):
                name = f"cronsun_web_sse_{field}"
                kind = "counter" if field.endswith("_total") else "gauge"
                lines.append(f"# TYPE {name} {kind}")
                lines.append(f"{name} {val}")
            if per_loop is not None:
                # a hot loop must be visible per loop, not averaged
                # away across the pool
                name = "cronsun_web_sse_loop_connections"
                lines.append(f"# TYPE {name} gauge")
                for i, nconns in enumerate(per_loop):
                    lines.append(f'{name}{{loop="{i}"}} {nconns}')
        seen_types: set = set()
        sched_snaps: list = []    # partitioned-plane aggregation input
        for kv in self._degraded_prefix(self.ks.metrics):
            rest = kv.key[len(self.ks.metrics):].split("/", 1)
            if len(rest) != 2:
                continue
            component, instance = rest
            try:
                snap = json.loads(kv.value)
            except json.JSONDecodeError:
                continue
            inst = _esc_label(instance)
            # partitioned scheduler plane: every sched series carries
            # its partition as a LABEL (a stalled partition must be
            # visible per series, not averaged away); unpartitioned
            # snapshots carry no partition field and render unchanged
            extra = ""
            if component == "sched":
                sched_snaps.append(snap)
                part = snap.get("partition")
                if isinstance(part, (int, float)):
                    extra = f',partition="{int(part)}"'
            # mesh plane: every cronsun_mesh_tick_* series carries the
            # demand wire format its ticks ran with (dense vs
            # compacted must be tellable apart per series — a format
            # flip mid-scrape-window is an auto-select event, not
            # noise); the string field itself renders only as this
            # label
            if component == "mesh":
                fmt = snap.get("demand_format")
                if isinstance(fmt, str) and fmt:
                    extra = f',demand_format="{_esc_label(fmt)}"'
            if component == "tenant":
                # per-tenant admission snapshots are NESTED
                # ({tenant: {field: n}}): render each numeric leaf as
                # cronsun_tenant_<field>{instance=,tenant=}
                for tname, fields in sorted(snap.items()):
                    if not isinstance(fields, dict):
                        continue
                    tn = _esc_label(tname)
                    for field, val in sorted(fields.items()):
                        if not isinstance(val, (int, float)):
                            continue
                        name = f"cronsun_tenant_{field}"
                        if name not in seen_types:
                            kind = ("counter"
                                    if field.endswith(("_total",
                                                       "_fires"))
                                    else "gauge")
                            lines.append(f"# TYPE {name} {kind}")
                            seen_types.add(name)
                        lines.append(
                            f'{name}{{instance="{inst}",'
                            f'tenant="{tn}"}} {val}')
                continue
            for field, val in sorted(snap.items()):
                if not isinstance(val, (int, float)):
                    continue
                if field == "partition" and extra:
                    continue    # rides every series as the label
                name = f"cronsun_{component}_{field}"
                if name not in seen_types:
                    kind = "counter" if field.endswith("_total") else "gauge"
                    lines.append(f"# TYPE {name} {kind}")
                    seen_types.add(name)
                lines.append(f'{name}{{instance="{inst}"{extra}}} {val}')
        # aggregate scheduler-plane view: sums over the LIVE leaders'
        # snapshots (one per partition when partitioned; the single
        # leader otherwise), so "what is the fleet dispatching" is one
        # series however many partitions tick behind it.  Gauges on
        # purpose — the leader set changes across failovers, so the
        # sums are not monotone.
        leaders = [s for s in sched_snaps if s.get("is_leader")]
        if leaders:
            led_parts = {int(s["partition"]) for s in leaders
                         if isinstance(s.get("partition"), (int, float))}
            lines.append("# TYPE cronsun_sched_fleet_leaders gauge")
            lines.append(f"cronsun_sched_fleet_leaders {len(leaders)}")
            lines.append("# TYPE cronsun_sched_fleet_partitions gauge")
            lines.append(f"cronsun_sched_fleet_partitions "
                         f"{max(len(led_parts), 1)}")
            for field in ("dispatches_total", "steps_total", "jobs",
                          "procs_running", "dispatch_queue_depth",
                          "overflow_drops_total",
                          "skipped_seconds_total",
                          "lease_resigns_total"):
                vals = [s.get(field) for s in leaders]
                vals = [v for v in vals if isinstance(v, (int, float))]
                if not vals:
                    continue
                name = f"cronsun_sched_fleet_{field}"
                lines.append(f"# TYPE {name} gauge")
                lines.append(f"{name} {sum(vals)}")
        # server-side op timings from BOTH backing servers (their own
        # op_stats op).  Store: names the component that owns a
        # dispatch-plane ceiling — claim paths, bulk writes, watch
        # fan-out — and, next to the scheduler's pipeline_stall_*
        # gauges, shows publisher backpressure without running a bench.
        # Logsink: the RESULT plane's attribution — create_job_logs
        # count vs the log_records tally gives the fleet's
        # records-per-flush (the coalescing win), and total_ms names
        # logd itself as (or rules it out as) the exec-lag ceiling.
        for backend, prefix in ((self.store, "store"),
                                (self.sink, "logsink")):
            # sharded store clients expose per-SHARD stats; with more
            # than one shard each series carries a ``shard`` label so
            # cronsun_store_op_* series from different shards don't
            # collide.  Single-shard output is byte-identical to the
            # unlabeled form below.
            labeled = None    # [(shard label or None, stats dict), ...]
            oss = getattr(backend, "op_stats_shards", None)
            if oss is not None:
                try:
                    parts = oss()
                    if len(parts) > 1:
                        labeled = list(enumerate(parts))
                    elif parts and parts[0]:
                        # one shard: unlabeled form, without re-fetching
                        # the same stats through op_stats() below
                        labeled = [(None, parts[0])]
                except Exception:  # noqa: BLE001 — degraded shard set
                    labeled = None
            if labeled is None:
                op_stats = getattr(backend, "op_stats", None)
                if op_stats is None:
                    continue
                try:
                    stats = op_stats()
                except Exception:  # noqa: BLE001 — older server
                    stats = {}
                if not stats:
                    continue
                labeled = [(None, stats)]
            for field, kind in (("count", "counter"),
                                ("total_ms", "counter"),
                                ("max_ms", "gauge")):
                name = f"cronsun_{prefix}_op_{field}"
                lines.append(f"# TYPE {name} {kind}")
                for si, stats in labeled:
                    shard = "" if si is None else f',shard="{si}"'
                    for op, ent in sorted(stats.items()):
                        if field not in ent:
                            continue
                        o = _esc_label(op)
                        lines.append(
                            f'{name}{{op="{o}"{shard}}} {ent[field]}')
            # per-shard brownout breakers (store/sharded.py PR 12):
            # state gauge (0 closed / 1 probing / 2 open), opens,
            # fail-fast refusals, and degraded partial reads — the
            # operator's first stop when one shard browns out.  Absent
            # entirely when the breaker is disabled.
            bs = getattr(backend, "breaker_snapshot", None)
            if bs is None:
                continue
            try:
                snaps = bs()
            except Exception:  # noqa: BLE001 — degraded shard set
                snaps = []
            if not snaps:
                continue
            state_num = {"closed": 0, "probing": 1, "open": 2}
            for field, kind in (
                    ("state", "gauge"),
                    ("opens_total", "counter"),
                    ("refused_total", "counter"),
                    ("degraded_reads_total", "counter")):
                name = f"cronsun_{prefix}_shard_breaker_{field}"
                lines.append(f"# TYPE {name} {kind}")
                for snap in snaps:
                    val = snap.get(field, 0)
                    if field == "state":
                        val = state_num.get(val, -1)
                    lines.append(
                        f'{name}{{shard="{snap["shard"]}"}} {val}')

        # store replication plane (repl/): per-replica role, lag, and
        # fencing epoch for every shard served by a replica group.
        # Absent entirely when nothing is replicated, so unreplicated
        # deployments' scrape output is unchanged.
        try:
            from ..repl import fleet_repl_status
            repl_shards = [
                e for e in fleet_repl_status(self.store)
                if any(isinstance(st, dict) and st.get("enabled")
                       for st in e.get("replicas", {}).values())]
        except Exception:  # noqa: BLE001 — degraded shard set
            repl_shards = []
        if repl_shards:
            role_num = {"leader": 1, "follower": 0}
            series = {"role": [], "lag_records": [],
                      "lag_seconds": [], "fencing_epoch": []}
            for e in repl_shards:
                for addr, st in sorted(e["replicas"].items()):
                    lbl = (f'shard="{e["shard"]}",'
                           f'replica="{_esc_label(addr)}"')
                    if not isinstance(st, dict) or not st.get("enabled"):
                        # unreachable replica: role -1 is the alert
                        series["role"].append((lbl, -1))
                        continue
                    series["role"].append(
                        (lbl, role_num.get(st.get("role"), -1)))
                    lag = st.get("lag_records")
                    series["lag_records"].append(
                        (lbl, lag if isinstance(lag, (int, float))
                         else -1))
                    series["lag_seconds"].append(
                        (lbl, st.get("lag_seconds") or 0.0))
                    series["fencing_epoch"].append(
                        (lbl, st.get("epoch", 0)))
            for field in ("role", "lag_records", "lag_seconds",
                          "fencing_epoch"):
                name = f"cronsun_store_repl_{field}"
                lines.append(f"# TYPE {name} gauge")
                for lbl, val in series[field]:
                    lines.append(f"{name}{{{lbl}}} {val}")

        def render_hist(name, label_kv, snap):
            """One Prometheus histogram (cumulative _bucket + _sum +
            _count) from a {buckets, sum, count} snapshot."""
            buckets = snap.get("buckets") or []
            lbl = "".join(f'{k}="{_esc_label(v)}",'
                          for k, v in label_kv)
            cum = 0
            for i, n in enumerate(buckets):
                cum += int(n)
                le = (f"{_trace.BUCKETS_MS[i]:g}"
                      if i < len(_trace.BUCKETS_MS) else "+Inf")
                lines.append(f'{name}_bucket{{{lbl}le="{le}"}} {cum}')
            lbl = lbl[:-1]
            lbl = f"{{{lbl}}}" if lbl else ""
            lines.append(f'{name}_sum{lbl} {snap.get("sum", 0)}')
            lines.append(f'{name}_count{lbl} {snap.get("count", 0)}')

        # trace plane: per-stage latency histograms from the logd
        # span rings (fixed buckets — summed across shards by the
        # sharded client, addable across web replicas by Prometheus)
        ts = getattr(self.sink, "trace_stats", None)
        if ts is not None:
            try:
                tstats = ts()
            except Exception:  # noqa: BLE001 — older/degraded sink
                tstats = None
            if tstats and tstats.get("stages"):
                name = "cronsun_trace_stage_ms"
                lines.append(f"# TYPE {name} histogram")
                for stage in _trace.STAGES:
                    ent = tstats["stages"].get(stage)
                    if ent:
                        render_hist(name, [("stage", stage)], ent)
                lines.append("# TYPE cronsun_trace_spans_total counter")
                lines.append(f"cronsun_trace_spans_total "
                             f"{tstats.get('spans_total', 0)}")
        # SLO engine: per-scope exec-latency histograms (every
        # execution, unbiased — the burn-rate source) + live burn
        # rates and alert states
        if self.slo_engine is not None:
            sums = self.slo_engine.scrape_sums()
            if sums:
                name = "cronsun_exec_latency_ms"
                lines.append(f"# TYPE {name} histogram")
                for scope in sorted(sums):
                    count, fail, sum_ms, buckets = sums[scope]
                    render_hist(name, [("scope", scope or "global")],
                                {"buckets": buckets, "count": count,
                                 "sum": round(sum_ms, 3)})
                lines.append("# TYPE cronsun_exec_fail_total counter")
                for scope in sorted(sums):
                    lines.append(
                        f'cronsun_exec_fail_total{{scope='
                        f'"{_esc_label(scope or "global")}"}} '
                        f'{sums[scope][1]}')
            snap = self.slo_engine.snapshot()
            if snap["slos"]:
                lines.append("# TYPE cronsun_slo_burn_rate gauge")
                for sname in sorted(snap["slos"]):
                    st = snap["slos"][sname]
                    for w, v in sorted(st["burn"].items()):
                        lines.append(
                            f'cronsun_slo_burn_rate{{slo='
                            f'"{_esc_label(sname)}",window="{w}"}} {v}')
                lines.append("# TYPE cronsun_slo_alert gauge")
                sev_num = {"": 0, "slow": 1, "fast": 2}
                for sname in sorted(snap["slos"]):
                    st = snap["slos"][sname]
                    lines.append(
                        f'cronsun_slo_alert{{slo="{_esc_label(sname)}"}}'
                        f' {sev_num.get(st["alert"], 0)}')
            for field, val in sorted(snap["stats"].items()):
                name = f"cronsun_{field}"
                lines.append(f"# TYPE {name} counter")
                lines.append(f"{name} {val}")
        return PlainText("\n".join(lines) + "\n")

    # ---- plumbing --------------------------------------------------------

    def handle(self, method: str, path: str, query: dict, body: bytes,
               cookies: dict, headers: Optional[dict] = None):
        """Transport-independent dispatch (tests call this directly)."""
        ctx = _Ctx(query, body, cookies, headers)
        for m, rx, fn, need_auth, need_admin in self.routes:
            if m != method:
                continue
            match = rx.match(path)
            if not match:
                continue
            ctx.path_args = match.groupdict()
            if need_auth or need_admin:
                if not self.auth_enabled:
                    ctx.session = self._implicit_admin
                else:
                    ctx.session = self.sessions.get(ctx.sid)
                    if ctx.session is None:
                        raise HttpError(401, "not logged in")
                    if need_admin and ctx.session.role != ROLE_ADMIN:
                        raise HttpError(403, "admin only")
            return fn(ctx), ctx
        raise HttpError(404, "no such route")

    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _run(self, method):
                parsed = urlparse(self.path)
                if parsed.path == "/" or parsed.path.startswith("/ui"):
                    page = INDEX_HTML.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/html")
                    self.send_header("Content-Length", str(len(page)))
                    self.end_headers()
                    self.wfile.write(page)
                    return
                query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                cookies = {}
                if self.headers.get("Cookie"):
                    c = SimpleCookie(self.headers["Cookie"])
                    cookies = {k: v.value for k, v in c.items()}
                ctype = "application/json"
                try:
                    result, ctx = server.handle(method, parsed.path, query,
                                                body, cookies,
                                                dict(self.headers))
                    if isinstance(result, SseStream):
                        # streaming escape hatch: no Content-Length
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "text/event-stream")
                        self.send_header("Cache-Control", "no-cache")
                        self.send_header("X-Accel-Buffering", "no")
                        for k, v in ctx.out_headers.items():
                            self.send_header(k, v)
                        self.end_headers()
                        pool = server._sse_pool
                        if pool is not None:
                            # epoll writer: mark the socket adopted
                            # (teardown skips it), hand it to the
                            # pool, and this request thread exits —
                            # 50k viewers, zero parked threads
                            self.close_connection = True
                            server._sse_adopt(self.connection)
                            pool.adopt(self.connection, result.client,
                                       result.replay)
                            return
                        # threaded writer (rollback): this request
                        # thread writes until the viewer drops, falls
                        # behind, or the server drains
                        result.serve(self.wfile)
                        return
                    if isinstance(result, PlainText):
                        payload = result.encode()
                        ctype = "text/plain; version=0.0.4"
                    else:
                        payload = json.dumps(result).encode()
                    self.send_response(ctx.out_status or 200)
                    for k, v in ctx.out_cookies.items():
                        self.send_header(
                            "Set-Cookie", f"sid={v}; Path=/; HttpOnly")
                    for k, v in ctx.out_headers.items():
                        self.send_header(k, v)
                except NotModified as e:
                    # per RFC 9110 a 304 carries no body — just the
                    # validator the cached response stays keyed on
                    self.send_response(304)
                    self.send_header("ETag", e.etag)
                    self.end_headers()
                    return
                except HttpError as e:
                    payload = json.dumps({"error": e.msg}).encode()
                    self.send_response(e.status)
                except Exception as e:  # noqa: BLE001
                    payload = json.dumps({"error": str(e)}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._run("GET")

            def do_PUT(self):
                self._run("PUT")

            def do_POST(self):
                self._run("POST")

            def do_DELETE(self):
                self._run("DELETE")

        class _Httpd(ThreadingHTTPServer):
            # socketserver's default listen backlog is 5: a viewer
            # fleet reconnecting en masse (replica restart, LB
            # failover) overflows it instantly and every dropped SYN
            # costs that client a full 1 s retransmit — measured
            # ~150 ms/conn average on a fast ramp, vs ~1 ms with a
            # real backlog.  The kernel clamps to net.core.somaxconn.
            request_queue_size = 1024

            def shutdown_request(httpd_self, request):
                # a socket adopted by the epoll pool outlives its
                # request thread: skipping the base teardown here is
                # what keeps socketserver's shutdown(SHUT_WR)+close
                # from half-closing a live stream under the pool
                if server._sse_forget(request):
                    return
                ThreadingHTTPServer.shutdown_request(httpd_self, request)

        self._httpd = _Httpd((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="api-server")
        t.start()
        return self

    def stop(self):
        # drain SSE viewers FIRST (final bye + long retry:, bounded
        # wait) so their writer threads close cleanly instead of dying
        # mid-write when the listener goes away
        if self._push is not None:
            self._push.stop(drain_timeout=2.0)
        if self._sse_pool is not None:
            self._sse_pool.stop()
            self._sse_pool = None
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        pool = getattr(self, "_scatter_pool_obj", None)
        if pool is not None:
            pool.shutdown(wait=False)
            self._scatter_pool_obj = None


class _Ctx:
    def __init__(self, query: dict, body: bytes, cookies: dict,
                 headers: Optional[dict] = None):
        self.query = query
        self.body = body
        self.cookies = cookies
        self.headers = headers or {}
        self.path_args: dict = {}
        self.session = None
        self.out_cookies: dict = {}
        self.out_headers: dict = {}
        self.out_status = 200     # handlers may override (503 readyz)

    @property
    def sid(self) -> str:
        return self.cookies.get("sid", "")

    def q(self, name: str) -> str:
        return self.query.get(name, "")

    def header(self, name: str) -> str:
        """Request header, case-insensitive."""
        for k, v in self.headers.items():
            if k.lower() == name.lower():
                return v
        return ""

    def q_int(self, name: str, default=None):
        """Query int with a 400 (not a 500) on malformed values."""
        raw = self.q(name)
        if not raw:
            return default
        try:
            return int(raw)
        except ValueError:
            raise HttpError(400, f"bad integer for {name!r}: {raw!r}")

    def q_float(self, name: str, default=None):
        raw = self.q(name)
        if not raw:
            return default
        try:
            return float(raw)
        except ValueError:
            raise HttpError(400, f"bad number for {name!r}: {raw!r}")

    def json(self) -> dict:
        if not self.body:
            return {}
        try:
            return json.loads(self.body)
        except json.JSONDecodeError:
            raise HttpError(400, "bad JSON body")

    def set_cookie(self, name: str, value: str):
        self.out_cookies[name] = value
