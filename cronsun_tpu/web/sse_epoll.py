"""Event-driven SSE writer: a fixed pool of epoll loops replaces
thread-per-connection.

The PR 17 threaded writer parks one Python thread per viewer in
``SseClient.take``; at 8 KiB of interpreter state plus a kernel stack
per thread the CPU host tops out near 1k viewers per replica.  This
module moves the write side onto ``selectors`` (epoll on Linux): a
small fixed pool of writer loops owns every SSE socket non-blocking,
so 50k idle connections cost 50k registered fds and ZERO threads.

Ownership and ordering:

- Each connection is adopted by exactly ONE loop at accept time and
  never migrates, so all writes to a socket happen on one thread —
  frames cannot reorder or interleave.  Per-event bytes come from the
  shared frame memo (``push.event_frame_tail``): serialize once,
  concatenate a per-viewer ``id:`` line, write to N sockets.
- Outbound bytes sit in a per-connection ring of WHOLE frames bounded
  by ``CRONSUN_SSE_SENDBUF`` bytes.  A viewer that stops reading first
  fills its kernel socket buffer (sendmsg -> EAGAIN, the loop arms
  EPOLLOUT and drains on writability), then overflows the ring: the
  backlog is dropped whole-frame (a partially sent frame's remainder
  is kept — the stream never tears mid-frame), ``lost`` is latched —
  the same terminal contract as the event-queue overflow — and the
  socket closes once the terminal frame drains.
- Heartbeats are swept from the loop tick: one ``monotonic()`` read
  per wakeup covers every idle connection the loop owns, instead of
  one per-connection timed condvar wait.

``SseClient`` stays the fan-out queue (cap / ``lost`` / ``stop``
semantics untouched); its ``signal`` hook wakes the owning loop via a
self-pipe.  The wire bytes are pinned byte-for-byte against the
threaded writer by tests/test_sse_epoll.py; ``CRONSUN_SSE_WRITER=
threads`` is the rollback switch.
"""

from __future__ import annotations

import os
import selectors
import threading
import time
from collections import deque
from itertools import islice
from typing import List, Optional

from ..metrics import LatencyRing
from .push import event_frame_tail

RETRY_PREAMBLE = b"retry: 3000\n\n"
LOST_FRAME = b"event: lost\ndata: {}\n\n"
BYE_FRAME = b"retry: 30000\nevent: bye\ndata: {}\n\n"
HB_FRAME = b": hb\n\n"

# sendmsg iovec batch bound: far below any real IOV_MAX (1024 on
# Linux) and large enough that a drain round trip covers a burst
_SENDMSG_MAX_BUFS = 64


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _frame_for(client, ev) -> bytes:
    """Advance the viewer cursor and build its frame: the per-viewer
    ``id:`` line + the memoized shared tail.  Byte-identical to the
    threaded writer's ``SseStream._event_bytes``."""
    client.advance(ev[0])
    cursor = ",".join(str(v) for v in client.vec)
    return b"id: " + cursor.encode("ascii") + b"\n" + event_frame_tail(ev)


class _Conn:
    """One adopted viewer socket, owned by exactly one writer loop."""

    __slots__ = ("sock", "fd", "client", "frames", "queued", "off",
                 "last_out", "closing", "want_w", "sig_ts")

    def __init__(self, sock, client, now: float):
        self.sock = sock
        self.fd = sock.fileno()
        self.client = client
        self.frames: deque = deque()  # whole SSE frames, FIFO
        self.queued = 0               # ring occupancy in bytes
        self.off = 0                  # sent prefix of frames[0]
        self.last_out = now           # heartbeat clock (loop tick time)
        self.closing = False          # terminal frame queued: close on drain
        self.want_w = False           # EVENT_WRITE armed
        self.sig_ts = 0.0             # pending-signal stamp (loop lag)


class _WriterLoop(threading.Thread):
    def __init__(self, pool: "EpollSsePool", idx: int):
        super().__init__(daemon=True, name=f"sse-epoll-{idx}")
        self.pool = pool
        self.idx = idx
        self.sel = selectors.DefaultSelector()
        r, w = os.pipe()
        os.set_blocking(r, False)
        os.set_blocking(w, False)
        self._rpipe, self._wpipe = r, w
        self.sel.register(r, selectors.EVENT_READ, None)
        self.mu = threading.Lock()
        self._adds: list = []       # (sock, client, init_frames)
        self._signaled: list = []   # _Conn with fresh queue state
        self.conns: dict = {}       # fd -> _Conn (loop thread only)
        self.nconns = 0             # adopted minus closed (cross-thread)
        self.lag = LatencyRing(cap=512)
        self._stopping = False
        self._last_sweep = 0.0

    # ---- cross-thread surface (HTTP handlers, push fan-out) --------------

    def wake(self):
        try:
            os.write(self._wpipe, b"\0")
        except (BlockingIOError, OSError):
            pass  # full pipe == wakeup already pending; closed == stopping

    def adopt(self, sock, client, init_frames: List[bytes]):
        with self.mu:
            self._adds.append((sock, client, init_frames))
            self.nconns += 1
        self.wake()

    def signal(self, conn: _Conn):
        """This viewer's queue changed (push / lost / stop)."""
        with self.mu:
            if conn.sig_ts == 0.0:
                conn.sig_ts = time.monotonic()
                self._signaled.append(conn)
        self.wake()

    def stop(self):
        self._stopping = True
        self.wake()

    # ---- the loop --------------------------------------------------------

    def run(self):
        hb = self.pool.heartbeat
        # one clock read per tick covers every idle conn this loop
        # owns; hb/4 granularity keeps the worst-case extra delay a
        # quarter beat (the threaded writer's condvar was exact, but
        # nothing on the wire contract depends on heartbeat phase)
        tick = min(1.0, max(0.05, hb / 4.0)) if hb > 0 else 1.0
        while not self._stopping:
            try:
                events = self.sel.select(timeout=tick)
            except OSError:
                events = []
            now = time.monotonic()
            for key, mask in events:
                if key.data is None:
                    self._drain_pipe()
                    continue
                conn = key.data
                if self.conns.get(conn.fd) is not conn:
                    continue
                if mask & selectors.EVENT_READ:
                    if not self._on_readable(conn):
                        continue
                if mask & selectors.EVENT_WRITE:
                    self._drain(conn, now)
            with self.mu:
                adds, self._adds = self._adds, []
                sigs, self._signaled = self._signaled, []
            for sock, client, init_frames in adds:
                self._register(sock, client, init_frames, now)
            for conn in sigs:
                with self.mu:
                    ts, conn.sig_ts = conn.sig_ts, 0.0
                if self.conns.get(conn.fd) is not conn:
                    continue
                if ts:
                    self.lag.add((now - ts) * 1000.0)
                self._pump(conn, now)
            if hb > 0 and now - self._last_sweep >= tick:
                self._last_sweep = now
                for conn in list(self.conns.values()):
                    if (not conn.closing and not conn.frames
                            and now - conn.last_out >= hb):
                        conn.frames.append(HB_FRAME)
                        conn.queued += len(HB_FRAME)
                        self._drain(conn, now)
        self._shutdown()

    def _drain_pipe(self):
        try:
            while os.read(self._rpipe, 4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _register(self, sock, client, init_frames, now: float):
        try:
            sock.setblocking(False)
            conn = _Conn(sock, client, now)
        except OSError:
            self._dispose(sock, client)
            return
        conn.frames.extend(init_frames)
        conn.queued = sum(len(f) for f in init_frames)
        try:
            self.sel.register(sock, selectors.EVENT_READ, conn)
        except (OSError, ValueError, KeyError):
            self._dispose(sock, client)
            return
        self.conns[conn.fd] = conn
        client.signal = (lambda loop=self, c=conn: loop.signal(c))
        # events that raced the handoff are sitting in the client
        # queue with no signal armed — pump once unconditionally
        self._pump(conn, now)

    def _on_readable(self, conn: _Conn) -> bool:
        """EVENT_READ on an SSE socket: either the browser went away
        (recv -> b"", the threaded writer only noticed at the next
        write) or it sent bytes we don't serve (ignored)."""
        try:
            d = conn.sock.recv(4096)
        except (BlockingIOError, InterruptedError):
            return True
        except OSError:
            self._close(conn)
            return False
        if not d:
            self._close(conn)
            return False
        return True

    def _pump(self, conn: _Conn, now: float):
        """Move queued events from the SseClient into the outbound
        ring as frames, append terminal frames, then drain."""
        if conn.closing:
            return
        evs, state = conn.client.take(timeout=0)
        if evs:
            frames = [_frame_for(conn.client, ev) for ev in evs]
            total = sum(len(f) for f in frames)
            if conn.queued + total > self.pool.sendbuf:
                self._evict(conn, now)
                return
            conn.frames.extend(frames)
            conn.queued += total
        if state == "lost":
            conn.frames.append(LOST_FRAME)
            conn.queued += len(LOST_FRAME)
            conn.closing = True
        elif state == "closed":
            conn.frames.append(BYE_FRAME)
            conn.queued += len(BYE_FRAME)
            conn.closing = True
        if conn.frames:
            self._drain(conn, now)

    def _evict(self, conn: _Conn, now: float):
        """Ring overflow: this viewer's kernel buffer AND its ring are
        full — the epoll layer's slow-consumer backpressure.  Drop the
        backlog whole-frame (the sent prefix of frames[0] is kept so
        the byte stream never tears mid-frame), latch ``lost``, close
        once the terminal frame drains.  Same contract as the
        event-queue overflow: the viewer re-lists and resumes."""
        keep: Optional[bytes] = None
        if conn.off and conn.frames:
            keep = conn.frames[0]
        conn.frames.clear()
        conn.queued = 0
        if keep is not None:
            conn.frames.append(keep)
            conn.queued = len(keep)
        conn.frames.append(LOST_FRAME)
        conn.queued += len(LOST_FRAME)
        conn.closing = True
        conn.client.mark_lost()
        pm = self.pool.manager
        pm.count("ring_evictions_total")
        pm.count("dropped_slow_total")
        pm.count("client_lost_total")
        self._drain(conn, now)

    def _drain(self, conn: _Conn, now: float):
        """Coalesced vectored write: every queued frame rides one
        ``sendmsg`` per _SENDMSG_MAX_BUFS, so a wakeup that fanned a
        burst to this viewer costs one syscall, not one per event."""
        sock = conn.sock
        while conn.frames:
            if conn.off:
                bufs = [memoryview(conn.frames[0])[conn.off:]]
                bufs.extend(islice(conn.frames, 1, _SENDMSG_MAX_BUFS))
            else:
                bufs = list(islice(conn.frames, 0, _SENDMSG_MAX_BUFS))
            try:
                n = sock.sendmsg(bufs)
            except (BlockingIOError, InterruptedError):
                self._want_write(conn, True)
                return
            except OSError:
                self._close(conn)
                return
            if n <= 0:
                self._want_write(conn, True)
                return
            conn.last_out = now
            n += conn.off
            conn.off = 0
            while conn.frames and n >= len(conn.frames[0]):
                f = conn.frames.popleft()
                n -= len(f)
                conn.queued -= len(f)
            conn.off = n
        self._want_write(conn, False)
        if conn.closing:
            self._close(conn)

    def _want_write(self, conn: _Conn, want: bool):
        if conn.want_w == want:
            return
        conn.want_w = want
        ev = selectors.EVENT_READ | (selectors.EVENT_WRITE if want else 0)
        try:
            self.sel.modify(conn.sock, ev, conn)
        except (KeyError, ValueError, OSError):
            pass

    def _close(self, conn: _Conn):
        if self.conns.get(conn.fd) is conn:
            del self.conns[conn.fd]
        conn.client.signal = None
        try:
            self.sel.unregister(conn.sock)
        except (KeyError, ValueError, OSError):
            pass
        try:
            conn.sock.close()
        except OSError:
            pass
        conn.frames.clear()
        conn.queued = 0
        with self.mu:
            self.nconns -= 1
        self.pool.on_close(conn.sock)
        self.pool.manager.unregister(conn.client)

    def _dispose(self, sock, client):
        """Adoption failed (socket died in the handoff window)."""
        try:
            sock.close()
        except OSError:
            pass
        with self.mu:
            self.nconns -= 1
        self.pool.on_close(sock)
        self.pool.manager.unregister(client)

    def _shutdown(self):
        with self.mu:
            adds, self._adds = self._adds, []
            self._signaled = []
        for sock, client, _frames in adds:
            self._dispose(sock, client)
        for conn in list(self.conns.values()):
            self._close(conn)
        try:
            self.sel.unregister(self._rpipe)
        except (KeyError, ValueError, OSError):
            pass
        self.sel.close()
        for fd in (self._rpipe, self._wpipe):
            try:
                os.close(fd)
            except OSError:
                pass

    # ---- observability (cross-thread, racy-read tolerant) ----------------

    def queue_depth(self) -> tuple:
        for _ in range(3):
            try:
                conns = list(self.conns.values())
                break
            except RuntimeError:  # resized mid-iteration; retry
                conns = []
        return (sum(c.queued for c in conns),
                sum(len(c.frames) for c in conns))


class EpollSsePool:
    """The replica's writer pool: ``CRONSUN_SSE_LOOPS`` epoll loops
    (default 2) splitting adopted sockets least-connections."""

    def __init__(self, manager, nloops: Optional[int] = None,
                 sendbuf: Optional[int] = None, on_close=None):
        self.manager = manager
        self.heartbeat = manager.heartbeat
        self.nloops = max(1, nloops if nloops is not None
                          else _env_int("CRONSUN_SSE_LOOPS", 2))
        self.sendbuf = max(4096, sendbuf if sendbuf is not None
                           else _env_int("CRONSUN_SSE_SENDBUF", 262144))
        # transport hook: the HTTP layer forgets its claim on an
        # adopted socket when the pool closes it
        self.on_close = on_close or (lambda sock: None)
        self.loops = [_WriterLoop(self, i) for i in range(self.nloops)]
        for lp in self.loops:
            lp.start()

    def adopt(self, sock, client, replay: list):
        """Take ownership of an accepted SSE socket (headers already
        sent).  The preamble + replay are enqueued unbounded — the
        threaded writer wrote them synchronously whatever their size,
        and the replay is already page-bounded by PushManager.replay —
        then the least-loaded loop registers the socket."""
        frames = [RETRY_PREAMBLE]
        frames.extend(_frame_for(client, ev) for ev in replay)
        loop = min(self.loops, key=lambda lp: lp.nconns)
        loop.adopt(sock, client, frames)

    def stop(self, timeout: float = 2.0):
        for lp in self.loops:
            lp.stop()
        deadline = time.monotonic() + max(0.0, timeout)
        for lp in self.loops:
            lp.join(timeout=max(0.05, deadline - time.monotonic()))

    def stats(self) -> dict:
        """Flat numeric gauges for /v1/metrics (rendered under the
        ``cronsun_web_sse_`` prefix) + the per-loop connection counts
        (rendered with a ``loop`` label)."""
        samples: list = []
        qbytes = qframes = 0
        per_loop = []
        for lp in self.loops:
            per_loop.append(max(0, lp.nconns))
            samples.extend(lp.lag._v)
            b, f = lp.queue_depth()
            qbytes += b
            qframes += f
        merged = LatencyRing(cap=len(samples) or 1)
        for s in samples:
            merged.add(s)
        return {
            "writer_loops": self.nloops,
            "loop_lag_p50_ms": round(merged.percentile(0.50), 3),
            "loop_lag_p99_ms": round(merged.percentile(0.99), 3),
            "write_queue_bytes": qbytes,
            "write_queue_frames": qframes,
            "loop_connections": per_loop,
        }
