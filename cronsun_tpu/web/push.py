"""Live-push plane for the web tier: logd change streams fanned out
to browsers over SSE.

The PR 7/9 poll path made every dashboard poll cheap (revision ETags,
304s, the response cache) — but read cost still scaled O(viewers x
poll rate) even when nothing changed.  This module inverts it: the web
server subscribes ONCE per logd shard (the ``subscribe`` wire op, both
backends) and

- keeps a push-maintained per-shard revision vector,
- refreshes the response cache's changed-shard partials on push
  (debounced) so the NEXT poll is a body hit instead of a recompute,
- fans event summaries out to SSE viewers through bounded per-client
  queues — a stalled browser overflows its own queue, gets a terminal
  ``lost`` event, and re-lists; it cannot buffer the fleet.

Loss semantics are the store's watch semantics end to end: a shard
subscription that overflows is resumed server-side at the manager's
vector (the subscribe op replays from its hot window); only when the
server declares a gap — the missed range left retention — do viewers
see ``lost``.

``CRONSUN_WEB_PUSH=off`` is the rollback switch: no subscriptions, no
/v1/stream (503), byte-identical poll behavior.
"""

from __future__ import annotations

import json
import os
import threading
from collections import OrderedDict, deque
from typing import List, Optional

from .. import log
from ..logsink.joblog import SubscriptionLost


def push_default() -> bool:
    return os.environ.get("CRONSUN_WEB_PUSH", "").lower() not in (
        "off", "0", "false")


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def event_dict(ev) -> dict:
    """SSE ``data:`` payload for one event summary — the _log_dict
    field names minus the heavy payload (user/command/output stay
    behind /v1/log/<id>)."""
    return {"id": ev[0], "jobId": ev[1], "jobGroup": ev[2],
            "name": ev[3], "node": ev[4], "success": ev[5],
            "beginTime": ev[6], "endTime": ev[7]}


_json_memo: "OrderedDict[tuple, str]" = OrderedDict()
_frame_memo: "OrderedDict[tuple, bytes]" = OrderedDict()
_json_memo_mu = threading.Lock()
_JSON_MEMO_CAP = 8192


def event_data_json(ev) -> str:
    """``data:`` line payload, memoized: every connected viewer
    serializes the SAME summary, so at N viewers the naive path pays
    N json.dumps per record — the memo makes fan-out cost one dumps
    per record plus N string copies.  Keyed by the WHOLE summary
    tuple, not the id: the memo is process-global and record ids are
    per-sink, so two sinks in one process (tests, a future
    multi-sink replica) would otherwise serve each other stale
    frames."""
    key = tuple(ev)
    with _json_memo_mu:
        s = _json_memo.get(key)
        if s is not None:
            return s
    s = json.dumps(event_dict(ev), separators=(",", ":"))
    with _json_memo_mu:
        _json_memo[key] = s
        while len(_json_memo) > _JSON_MEMO_CAP:
            _json_memo.popitem(last=False)
    return s


def event_frame_tail(ev) -> bytes:
    """The per-event constant SSE frame suffix
    (``event: log\\ndata: <json>\\n\\n``), memoized like
    :func:`event_data_json` (same whole-tuple key).  Only the ``id:``
    line differs per viewer (it carries that viewer's cursor vector),
    so both writers serialize AND encode each record once per
    replica; fan-out to N viewers is N cheap concatenations."""
    key = tuple(ev)
    with _json_memo_mu:
        b = _frame_memo.get(key)
        if b is not None:
            return b
    b = (b"event: log\ndata: " + event_data_json(ev).encode() + b"\n\n")
    with _json_memo_mu:
        _frame_memo[key] = b
        while len(_frame_memo) > _JSON_MEMO_CAP:
            _frame_memo.popitem(last=False)
    return b


class SseClient:
    """One viewer: a bounded event queue plus its server-side filters.
    Overflow clears the queue and latches ``lost`` (watch semantics —
    the writer sends a terminal ``lost`` event and the browser
    re-lists), so a slow consumer's cost is capped at ``cap`` summaries
    however far it falls behind."""

    def __init__(self, filters: dict, cap: int, vec: List[int],
                 nshards: int):
        self.filters = filters
        self.cap = max(1, int(cap))
        self.vec = list(vec)          # delivered cursor (id: field)
        self.reg_vec = list(vec)      # fan-out starts past this point
        self.nshards = nshards
        self._mu = threading.Lock()
        self._cv = threading.Condition(self._mu)
        self._buf: deque = deque()
        self.lost = False
        self.stopping = False
        # event-driven writer hook (web/sse_epoll.py): wakes the epoll
        # loop that owns this viewer's socket whenever the queue state
        # changes.  None under the threaded writer — take() blocks on
        # the condvar instead.
        self.signal = None

    def matches(self, ev) -> bool:
        f = self.filters
        tids = f.get("tenant_ids")
        if tids is not None and ev[1] not in tids:
            return False
        jids = f.get("job_ids")
        if jids is not None and ev[1] not in jids:
            return False
        node = f.get("node")
        if node and ev[4] != node:
            return False
        if f.get("failed_only") and ev[5]:
            return False
        return True

    def push(self, evs) -> bool:
        """Queue events for the writer; returns False when this client
        just overflowed (caller counts the drop)."""
        with self._cv:
            if self.lost or self.stopping:
                return True
            if len(self._buf) + len(evs) > self.cap:
                self._buf.clear()
                self.lost = True
                self._cv.notify_all()
                self._signal()
                return False
            self._buf.extend(evs)
            self._cv.notify_all()
            self._signal()
            return True

    def mark_lost(self):
        with self._cv:
            self._buf.clear()
            self.lost = True
            self._cv.notify_all()
            self._signal()

    def stop(self):
        with self._cv:
            self.stopping = True
            self._cv.notify_all()
            self._signal()

    def _signal(self):
        sig = self.signal
        if sig is not None:
            try:
                sig()
            except Exception:  # noqa: BLE001 — a dying loop can't veto
                pass           # the fan-out path; the pool reaps it

    def take(self, timeout: Optional[float]):
        """-> (events, state): state is None (keep streaming), "lost"
        (send terminal lost + close) or "closed" (graceful drain)."""
        with self._cv:
            if not self._buf and not self.lost and not self.stopping:
                self._cv.wait(timeout)
            evs = list(self._buf)
            self._buf.clear()
            state = "lost" if self.lost else (
                "closed" if self.stopping else None)
            return evs, state

    def advance(self, eid: int):
        if self.nshards > 1:
            raw, si = eid // self.nshards, eid % self.nshards
            if raw > self.vec[si]:
                self.vec[si] = raw
        elif eid > self.vec[0]:
            self.vec[0] = eid


class PushManager:
    """Per-shard logd subscriptions + SSE fan-out + the debounced
    cache-refresh signal.  One instance per ApiServer."""

    def __init__(self, sink, on_change=None,
                 heartbeat: Optional[float] = None,
                 client_cap: Optional[int] = None,
                 sub_cap: int = 8192):
        self.sink = sink
        # raw shard clients when sharded (a stream failure latches lost
        # and this manager re-subscribes — that IS the breaker story;
        # routing streams through breaker guards would just add a
        # second failure detector), the sink itself otherwise
        self.shards = list(getattr(sink, "_raw", None) or [sink])
        self.nshards = max(1, int(getattr(sink, "nshards", 1)))
        self.on_change = on_change      # debounced: cache refresh hook
        self.heartbeat = heartbeat if heartbeat is not None else \
            _env_float("CRONSUN_SSE_HEARTBEAT", 15.0)
        self.client_cap = client_cap if client_cap is not None else \
            _env_int("CRONSUN_SSE_QUEUE", 256)
        self.sub_cap = sub_cap
        self._mu = threading.Lock()
        self._clients: list = []
        self._vec = [0] * self.nshards
        self._subs: list = [None] * self.nshards
        self._health: list = [(False, "connecting")] * self.nshards
        self._stats = {"events_total": 0, "dropped_slow_total": 0,
                       "resumes_total": 0, "cache_refreshes_total": 0,
                       "client_lost_total": 0,
                       "ring_evictions_total": 0}
        self._stop = threading.Event()
        self._dirty = threading.Event()
        self._threads: list = []
        self.running = False

    # ---- lifecycle -------------------------------------------------------

    def start(self) -> "PushManager":
        """Subscribe every shard (synchronously — readiness is truthful
        from the first /readyz) and start the drain + refresh threads.
        A shard that fails to subscribe here starts unhealthy and the
        drain loop keeps retrying with backoff."""
        for si in range(self.nshards):
            try:
                self._subscribe(si, after_id=0)
            except Exception as e:  # noqa: BLE001 — retried in the loop
                self._health[si] = (False, f"subscribe failed: {e}")
        self.running = True
        for si in range(self.nshards):
            t = threading.Thread(target=self._shard_loop, args=(si,),
                                 daemon=True, name=f"web-push-{si}")
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._refresh_loop, daemon=True,
                             name="web-push-refresh")
        t.start()
        self._threads.append(t)
        return self

    def stop(self, drain_timeout: float = 2.0):
        """Graceful drain: viewers get a final ``bye`` event (with a
        long ``retry:`` so browsers back off the dead replica) and the
        writer threads close their sockets; bounded wait, then the
        subscriptions come down."""
        self._stop.set()
        self._dirty.set()
        with self._mu:
            clients = list(self._clients)
        for c in clients:
            c.stop()
        deadline = _mono() + max(0.0, drain_timeout)
        while _mono() < deadline:
            with self._mu:
                if not self._clients:
                    break
            _sleep(0.02)
        with self._mu:
            subs, self._subs = self._subs, [None] * self.nshards
        for s in subs:
            if s is not None:
                try:
                    s.close()
                except Exception:  # noqa: BLE001 — teardown
                    pass
        self.running = False

    # ---- the per-shard subscription loops --------------------------------

    def _subscribe(self, si: int, after_id: int):
        """(Re)open shard ``si``'s stream.  A successful subscribe with
        a replayable window recovers every missed event server-side; a
        declared gap is unrecoverable — viewers get ``lost`` and
        re-list."""
        sub = self.shards[si].subscribe(after_id=after_id,
                                        cap=self.sub_cap)
        with self._mu:
            old = self._subs[si]
            self._subs[si] = sub
            if after_id <= 0 or sub.gap:
                self._vec[si] = sub.rev
            self._health[si] = (True, f"subscribed at {sub.rev}")
        if old is not None:
            try:
                old.close()
            except Exception:  # noqa: BLE001
                pass
        if after_id > 0 and sub.gap:
            # the missed range left the server's replay window: the
            # store's lossy contract reaches the viewers
            self._evict_all("shard %d resume gap" % si)
        return sub

    def _shard_loop(self, si: int):
        backoff = 0.2
        while not self._stop.is_set():
            with self._mu:
                sub = self._subs[si]
            if sub is None:
                try:
                    self._subscribe(si, after_id=self._vec[si])
                    backoff = 0.2
                except Exception as e:  # noqa: BLE001 — keep retrying
                    with self._mu:
                        self._health[si] = (
                            False, f"resubscribe failed: {e}")
                    self._stop.wait(backoff)
                    backoff = min(backoff * 2, 5.0)
                continue
            try:
                evs = sub.get(timeout=0.5)
            except SubscriptionLost:
                with self._mu:
                    if self._subs[si] is sub:
                        self._subs[si] = None
                    self._health[si] = (False, "stream lost; resuming")
                continue
            if evs:
                self._apply(si, evs)

    def _apply(self, si: int, evs):
        """One batch from shard ``si``: encode ids to the global space,
        advance the vector, fan out, signal the cache refresher."""
        n = self.nshards
        if n > 1:
            enc = [(e[0] * n + si,) + tuple(e[1:]) for e in evs]
        else:
            enc = [tuple(e) for e in evs]
        with self._mu:
            if evs[-1][0] > self._vec[si]:
                self._vec[si] = evs[-1][0]
            clients = list(self._clients)
        delivered = 0
        for c in clients:
            out = [e for e in enc if c.matches(e)]
            if not out:
                continue
            if c.push(out):
                delivered += len(out)
            else:
                self.count("dropped_slow_total")
                self.count("client_lost_total")
        if delivered:
            self.count("events_total", delivered)
        self._dirty.set()

    def _evict_all(self, why: str):
        with self._mu:
            clients = list(self._clients)
        if clients:
            log.warnf("push: evicting %d sse client(s): %s",
                      len(clients), why)
        for c in clients:
            c.mark_lost()
            self.count("client_lost_total")

    def _refresh_loop(self):
        """Debounced cache refresh: coalesce event bursts for ~50 ms,
        then recompute only the changed shards' cached partials (the
        on_change hook is ApiServer._push_refresh)."""
        while not self._stop.is_set():
            self._dirty.wait()
            if self._stop.is_set():
                return
            self._dirty.clear()
            _sleep(0.05)
            self._dirty.clear()
            cb = self.on_change
            if cb is None:
                continue
            try:
                if cb():
                    self.count("cache_refreshes_total")
            except Exception as e:  # noqa: BLE001 — next burst retries
                log.warnf("push: cache refresh failed: %s", e)

    # ---- viewer surface --------------------------------------------------

    def vector(self) -> List[int]:
        """Push-maintained per-shard cursor (len == nshards; len 1 for
        an unsharded sink)."""
        with self._mu:
            return list(self._vec)

    def register(self, filters: dict, cap: Optional[int] = None
                 ) -> SseClient:
        with self._mu:
            c = SseClient(filters, cap or self.client_cap, self._vec,
                          self.nshards)
            self._clients.append(c)
            return c

    def unregister(self, client: SseClient):
        with self._mu:
            try:
                self._clients.remove(client)
            except ValueError:
                pass

    def replay(self, client: SseClient, cursor_vec: List[int],
               max_pages: int = 10) -> list:
        """Resume: the records in (cursor, registration-vector] as
        event tuples, via the PR 7 cursor query (bounded —
        ``max_pages`` x 500; a client further behind than that is
        marked ``lost`` and re-lists).  Events already past the
        registration vector are skipped: they arrive through the live
        queue, so resume is exactly-once."""
        self.count("resumes_total")
        n = self.nshards
        after = list(cursor_vec) if n > 1 else cursor_vec[0]
        out = []
        for _ in range(max_pages):
            recs, _total = self.sink.query_logs(after_id=after,
                                                page=1, page_size=500)
            for r in recs:
                if r.id is None:
                    continue
                if n > 1:
                    raw, si = r.id // n, r.id % n
                    if raw > after[si]:
                        after[si] = raw
                    if raw > client.reg_vec[si]:
                        continue    # will arrive via the live queue
                else:
                    after = max(after, r.id)
                    if r.id > client.reg_vec[0]:
                        continue
                ev = (r.id, r.job_id, r.job_group, r.name, r.node,
                      r.success, r.begin_ts, r.end_ts)
                if client.matches(ev):
                    out.append(ev)
            if len(recs) < 500:
                return out
        client.mark_lost()          # too far behind: re-list
        return out

    # ---- observability ---------------------------------------------------

    def count(self, stat: str, n: int = 1):
        with self._mu:
            self._stats[stat] += n

    def stats(self) -> dict:
        with self._mu:
            out = dict(self._stats)
            out["connections"] = len(self._clients)
            return out

    def health(self) -> list:
        """[(ok, detail)] per shard — /readyz's named checks."""
        with self._mu:
            return list(self._health)


def _mono() -> float:
    import time
    return time.monotonic()


def _sleep(s: float):
    import time
    time.sleep(s)
