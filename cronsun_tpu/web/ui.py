"""Single-file management UI (replaces the reference's Vue SPA, web/ui/).

Functionally equivalent surface against the same /v1 REST API: dashboard
overview, job CRUD + pause + run-now, node list with liveness, node groups,
execution logs with filters, executing view.  Zero build step: one HTML
string served at /ui/.
"""

INDEX_HTML = r"""<!doctype html>
<html><head><meta charset="utf-8"><title>cronsun-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f5f6f8;color:#222}
 header{background:#1a2733;color:#fff;padding:10px 18px;display:flex;gap:18px;align-items:center}
 header b{font-size:17px} header a{color:#cfd8e3;cursor:pointer;text-decoration:none;padding:4px 8px;border-radius:4px}
 header a.active,header a:hover{background:#2e4052;color:#fff}
 main{padding:18px;max-width:1100px;margin:auto}
 table{border-collapse:collapse;width:100%;background:#fff;box-shadow:0 1px 2px #0002}
 th,td{padding:7px 10px;border-bottom:1px solid #e7eaee;text-align:left;font-size:13.5px;vertical-align:top}
 th{background:#eef1f5} tr:hover td{background:#f7fafd}
 .ok{color:#0a7d38}.bad{color:#c0392b}.muted{color:#888}
 button{background:#2d6cdf;color:#fff;border:0;border-radius:4px;padding:5px 11px;cursor:pointer;font-size:13px}
 button.warn{background:#c0392b} button.plain{background:#7c8aa0}
 input,select,textarea{padding:6px;border:1px solid #c8d0da;border-radius:4px;font-size:13.5px}
 .cards{display:flex;gap:14px;margin-bottom:18px;flex-wrap:wrap}
 .card{background:#fff;box-shadow:0 1px 2px #0002;border-radius:6px;padding:14px 20px;min-width:130px}
 .card .n{font-size:26px;font-weight:600}.card .t{color:#778;font-size:12.5px}
 #login{max-width:320px;margin:90px auto;background:#fff;padding:26px;border-radius:8px;box-shadow:0 2px 8px #0003;display:flex;flex-direction:column;gap:10px}
 dialog{border:0;border-radius:8px;box-shadow:0 4px 20px #0005;padding:20px;min-width:520px}
 dialog label{display:block;margin:8px 0 2px;font-size:12.5px;color:#556}
 dialog input,dialog select,dialog textarea{width:100%;box-sizing:border-box}
 .row{display:flex;gap:10px}.row>*{flex:1}
 pre{white-space:pre-wrap;background:#0e1620;color:#d7e3ef;padding:10px;border-radius:6px;max-height:300px;overflow:auto}
 .bar{display:flex;gap:8px;margin-bottom:12px;align-items:center;flex-wrap:wrap}
</style></head><body>
<header><b>cronsun-tpu</b>
 <a data-v=dash>Dashboard</a><a data-v=jobs>Jobs</a><a data-v=nodes>Nodes</a>
 <a data-v=groups>Groups</a><a data-v=logs>Logs</a><a data-v=exec>Executing</a>
 <a data-v=accounts id=nav-acc style="display:none">Accounts</a>
 <span style="flex:1"></span><a data-v=profile id=who class=muted></a><a id=logout>logout</a>
</header>
<main id=main></main>
<script>
const $=s=>document.querySelector(s);
const api=async(m,p,b)=>{const r=await fetch(p,{method:m,headers:{'Content-Type':'application/json'},
  body:b?JSON.stringify(b):undefined});const d=await r.json().catch(()=>({}));
  if(r.status===401){login();throw 'auth'}if(!r.ok)throw (d.error||r.status);return d};
const esc=s=>String(s??'').replace(/[&<>"]/g,c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;'}[c]));
const ts=t=>t?new Date(t*1000).toLocaleString():'';
let view='dash',me={};
function login(){$('#main').innerHTML=`<form id=login>
 <b>Sign in</b><input id=em placeholder=email value="admin@admin.com">
 <input id=pw type=password placeholder=password value="admin">
 <button>Login</button><span id=err class=bad></span></form>`;
 $('#login').onsubmit=async e=>{e.preventDefault();try{
  const d=await api('GET','/v1/session?email='+encodeURIComponent($('#em').value)+'&password='+encodeURIComponent($('#pw').value));
  me=d;$('#who').textContent=d.email;$('#nav-acc').style.display=d.role===1?'':'none';
  nav(view)}catch(x){$('#err').textContent=x}}}
$('#logout').onclick=async()=>{await api('DELETE','/v1/session');login()};
document.querySelectorAll('header a[data-v]').forEach(a=>a.onclick=()=>nav(a.dataset.v));
function nav(v){view=v;document.querySelectorAll('header a[data-v]').forEach(a=>
 a.classList.toggle('active',a.dataset.v===v));render[v]().catch(e=>{if(e!=='auth')$('#main').innerHTML='<p class=bad>'+esc(e)+'</p>'})}
const render={
 async dash(){const o=await api('GET','/v1/info/overview');
  $('#main').innerHTML=`<div class=cards>
   <div class=card><div class=n>${o.totalJobs}</div><div class=t>jobs</div></div>
   <div class=card><div class=n>${o.nodeAlived}</div><div class=t>nodes alive</div></div>
   <div class=card><div class=n>${o.jobExecuted.total}</div><div class=t>executions</div></div>
   <div class=card><div class=n class=ok>${o.jobExecuted.successed}</div><div class=t>succeeded</div></div>
   <div class=card><div class=n class=bad>${o.jobExecuted.failed}</div><div class=t>failed</div></div></div>
  <h3>Daily</h3><table><tr><th>day</th><th>total</th><th>success</th><th>failed</th></tr>
  ${o.jobExecutedDaily.map(d=>`<tr><td>${d.day}</td><td>${d.total}</td><td class=ok>${d.successed}</td><td class=bad>${d.failed}</td></tr>`).join('')}</table>`},
 async jobs(){const js=await api('GET','/v1/jobs');
  $('#main').innerHTML=`<div class=bar><button onclick="editJob()">+ New job</button></div>
  <table><tr><th>name</th><th>group</th><th>command</th><th>kind</th><th>timers</th><th>status</th><th></th></tr>
  ${js.map(j=>`<tr><td>${esc(j.name)}</td><td>${esc(j.group)}</td><td><code>${esc(j.command)}</code></td>
   <td>${['Common','Alone','Interval'][j.kind]||j.kind}</td>
   <td>${(j.rules||[]).map(r=>esc(r.timer)).join('<br>')}</td>
   <td>${j.pause?'<span class=muted>paused</span>':'<span class=ok>active</span>'}</td>
   <td><button class=plain onclick='editJob(${JSON.stringify(j)})'>edit</button>
    <button class=plain onclick="toggleJob('${j.group}','${j.id}',${!j.pause})">${j.pause?'resume':'pause'}</button>
    <button onclick="runNow('${j.group}','${j.id}')">run</button>
    <button class=warn onclick="delJob('${j.group}','${j.id}')">del</button></td></tr>`).join('')}</table>`},
 async nodes(){const ns=await api('GET','/v1/nodes');
  $('#main').innerHTML=`<table><tr><th>id</th><th>hostname</th><th>pid</th><th>version</th><th>up since</th><th>status</th></tr>
  ${ns.map(n=>`<tr><td>${esc(n.id)}</td><td>${esc(n.hostname)}</td><td>${n.pid}</td><td>${esc(n.version)}</td>
   <td>${ts(n.up_ts)}</td><td>${n.connected?'<span class=ok>connected</span>':'<span class=bad>down</span>'}</td></tr>`).join('')}</table>`},
 async groups(){const gs=await api('GET','/v1/node/groups');
  $('#main').innerHTML=`<div class=bar><button onclick="editGroup()">+ New group</button></div>
  <table><tr><th>id</th><th>name</th><th>nodes</th><th></th></tr>
  ${gs.map(g=>`<tr><td>${esc(g.id)}</td><td>${esc(g.name)}</td><td>${(g.nids||[]).map(esc).join(', ')}</td>
   <td><button class=plain onclick='editGroup(${JSON.stringify(g)})'>edit</button>
   <button class=warn onclick="delGroup('${g.id}')">del</button></td></tr>`).join('')}</table>`},
 async logs(){const failed=$('#flt')?.checked?'&failedOnly=true':'';
  const d=await api('GET','/v1/logs?pageSize=100'+failed);
  $('#main').innerHTML=`<div class=bar><label><input type=checkbox id=flt onchange="nav('logs')"> failed only</label>
   <span class=muted>${d.total} records</span></div>
  <table><tr><th>job</th><th>node</th><th>begin</th><th>secs</th><th>ok</th><th>output</th></tr>
  ${d.list.map(l=>`<tr><td>${esc(l.name)}</td><td>${esc(l.node)}</td><td>${ts(l.beginTime)}</td>
   <td>${(l.endTime-l.beginTime).toFixed(1)}</td>
   <td>${l.success?'<span class=ok>✓</span>':'<span class=bad>✗</span>'}</td>
   <td><code>${esc((l.output||'').slice(0,160))}</code></td></tr>`).join('')}</table>`},
 async exec(){const xs=await api('GET','/v1/job/executing');
  $('#main').innerHTML=`<table><tr><th>node</th><th>group</th><th>job</th><th>pid</th><th>since</th></tr>
  ${xs.map(x=>`<tr><td>${esc(x.node)}</td><td>${esc(x.group)}</td><td>${esc(x.jobId)}</td>
   <td>${esc(x.pid)}</td><td>${ts(x.time)}</td></tr>`).join('')||'<tr><td colspan=5 class=muted>nothing running</td></tr>'}</table>`},
 async accounts(){const as=await api('GET','/v1/admin/accounts');
  $('#main').innerHTML=`<div class=bar><button onclick="editAccount()">+ New account</button></div>
  <table><tr><th>email</th><th>role</th><th>status</th><th></th></tr>
  ${as.map(a=>`<tr><td>${esc(a.email)}${a.unchangeable?' <span class=muted>(built-in)</span>':''}</td>
   <td>${a.role===1?'Administrator':'Developer'}</td>
   <td>${a.status===1?'<span class=ok>enabled</span>':'<span class=bad>banned</span>'}</td>
   <td><button class=plain onclick='editAccount(${JSON.stringify(a)})'>edit</button></td></tr>`).join('')}</table>`},
 async profile(){
  $('#main').innerHTML=`<h3>Profile — ${esc(me.email||'')}</h3>
  <form id=pf style="max-width:340px;display:flex;flex-direction:column;gap:8px;background:#fff;padding:18px;border-radius:8px;box-shadow:0 1px 2px #0002">
   <label>current password</label><input id=po type=password>
   <label>new password</label><input id=pn type=password>
   <label>repeat new password</label><input id=pn2 type=password>
   <button>Change password</button><span id=pmsg></span></form>`;
  $('#pf').onsubmit=async e=>{e.preventDefault();const m=$('#pmsg');
   if($('#pn').value!==$('#pn2').value){m.className='bad';m.textContent='passwords differ';return}
   try{await api('POST','/v1/user/setpwd',{password:$('#po').value,newPassword:$('#pn').value});
    m.className='ok';m.textContent='password changed'}catch(x){m.className='bad';m.textContent=x}}},
};
window.editAccount=(a)=>{a=a||{};
 document.body.insertAdjacentHTML('beforeend',`<dialog id=dlg><form method=dialog>
  <b>${a.email?'Edit':'New'} account</b>
  <label>email</label><input id=ae value="${esc(a.email||'')}" ${a.email?'disabled':''}>
  <div class=row><div><label>role</label><select id=ar>
    <option value=2 ${a.role!==1?'selected':''}>Developer</option>
    <option value=1 ${a.role===1?'selected':''}>Administrator</option></select></div>
  <div><label>status</label><select id=as_>
    <option value=1 ${a.status!==0?'selected':''}>enabled</option>
    <option value=0 ${a.status===0?'selected':''}>banned</option></select></div></div>
  <label>password ${a.email?'(leave empty to keep)':''}</label><input id=ap type=password>
  <div class=bar style="margin-top:14px"><button id=sv>Save</button><button class=plain>Cancel</button></div>
 </form></dialog>`);const dlg=$('#dlg');dlg.showModal();dlg.onclose=()=>dlg.remove();
 $('#sv').onclick=async e=>{e.preventDefault();try{
  const body={email:a.email||$('#ae').value,role:+$('#ar').value,status:+$('#as_').value};
  if($('#ap').value)body.password=$('#ap').value;
  await api(a.email?'POST':'PUT','/v1/admin/account',body);
  dlg.close();nav('accounts')}catch(x){alert(x)}}};
window.toggleJob=async(g,id,p)=>{await api('POST',`/v1/job/${g}-${id}`,{pause:p});nav('jobs')};
window.runNow=async(g,id)=>{await api('PUT',`/v1/job/${g}-${id}/execute?node=`);alert('dispatched')};
window.delJob=async(g,id)=>{if(confirm('delete job?')){await api('DELETE',`/v1/job/${g}-${id}`);nav('jobs')}};
window.delGroup=async id=>{if(confirm('delete group?')){await api('DELETE','/v1/node/group/'+id);nav('groups')}};
window.editJob=(j)=>{j=j||{rules:[{}]};const r=(j.rules&&j.rules[0])||{};
 document.body.insertAdjacentHTML('beforeend',`<dialog id=dlg><form method=dialog>
  <b>${j.id?'Edit':'New'} job</b>
  <div class=row><div><label>name</label><input id=jn value="${esc(j.name||'')}"></div>
  <div><label>group</label><input id=jg value="${esc(j.group||'default')}"></div></div>
  <label>command</label><textarea id=jc rows=2>${esc(j.command||'')}</textarea>
  <div class=row><div><label>kind</label><select id=jk>
    <option value=0 ${j.kind==0?'selected':''}>Common (all eligible nodes)</option>
    <option value=1 ${j.kind==1?'selected':''}>Alone (exactly one)</option>
    <option value=2 ${j.kind==2?'selected':''}>Interval (one per interval)</option></select></div>
  <div><label>user</label><input id=ju value="${esc(j.user||'')}"></div></div>
  <div class=row><div><label>timeout s</label><input id=jt type=number value="${j.timeout||0}"></div>
  <div><label>retry</label><input id=jr type=number value="${j.retry||0}"></div>
  <div><label>parallels</label><input id=jp type=number value="${j.parallels||0}"></div></div>
  <label>cron timer (sec min hour dom month dow)</label><input id=rt value="${esc(r.timer||'0 */5 * * * *')}">
  <div class=row><div><label>node ids (comma)</label><input id=rn value="${esc((r.nids||[]).join(','))}"></div>
  <div><label>group ids</label><input id=rg value="${esc((r.gids||[]).join(','))}"></div>
  <div><label>exclude nodes</label><input id=rx value="${esc((r.exclude_nids||[]).join(','))}"></div></div>
  <div class=bar style="margin-top:14px"><button id=sv>Save</button><button class=plain>Cancel</button></div>
 </form></dialog>`);const dlg=$('#dlg');dlg.showModal();dlg.onclose=()=>dlg.remove();
 $('#sv').onclick=async e=>{e.preventDefault();const csv=v=>v.split(',').map(s=>s.trim()).filter(Boolean);
  try{await api('PUT','/v1/job',{id:j.id,name:$('#jn').value,group:$('#jg').value,oldGroup:j.group,
   command:$('#jc').value,kind:+$('#jk').value,user:$('#ju').value,timeout:+$('#jt').value,
   retry:+$('#jr').value,parallels:+$('#jp').value,pause:!!j.pause,
   rules:[{id:r.id,timer:$('#rt').value,nids:csv($('#rn').value),gids:csv($('#rg').value),
           exclude_nids:csv($('#rx').value)}]});dlg.close();nav('jobs')}catch(x){alert(x)}}};
window.editGroup=(g)=>{g=g||{};
 document.body.insertAdjacentHTML('beforeend',`<dialog id=dlg><form method=dialog>
  <b>${g.id?'Edit':'New'} group</b>
  <label>name</label><input id=gn value="${esc(g.name||'')}">
  <label>node ids (comma)</label><input id=gm value="${esc((g.nids||[]).join(','))}">
  <div class=bar style="margin-top:14px"><button id=sv>Save</button><button class=plain>Cancel</button></div>
 </form></dialog>`);const dlg=$('#dlg');dlg.showModal();dlg.onclose=()=>dlg.remove();
 $('#sv').onclick=async e=>{e.preventDefault();try{
  await api('PUT','/v1/node/group',{id:g.id,name:$('#gn').value,
   nids:$('#gm').value.split(',').map(s=>s.trim()).filter(Boolean)});dlg.close();nav('groups')}catch(x){alert(x)}}};
api('GET','/v1/session/me').then(d=>{me=d;$('#who').textContent=d.email;
 $('#nav-acc').style.display=d.role===1?'':'none';nav('dash')}).catch(()=>login());
</script></body></html>
"""
