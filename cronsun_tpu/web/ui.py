"""Single-file management UI (replaces the reference's Vue SPA, web/ui/).

Functionally equivalent surface against the same /v1 REST API: dashboard
overview, job CRUD + pause + run-now, node list with liveness, node groups,
execution logs with filters, executing view, account administration,
profile/set-password — with en / zh-CN i18n (reference web/ui/src/i18n/).
Zero build step: one HTML string served at /ui/.
"""

INDEX_HTML = r"""<!doctype html>
<html><head><meta charset="utf-8"><title>cronsun-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f5f6f8;color:#222}
 header{background:#1a2733;color:#fff;padding:10px 18px;display:flex;gap:18px;align-items:center}
 header b{font-size:17px} header a{color:#cfd8e3;cursor:pointer;text-decoration:none;padding:4px 8px;border-radius:4px}
 header a.active,header a:hover{background:#2e4052;color:#fff}
 main{padding:18px;max-width:1100px;margin:auto}
 table{border-collapse:collapse;width:100%;background:#fff;box-shadow:0 1px 2px #0002}
 th,td{padding:7px 10px;border-bottom:1px solid #e7eaee;text-align:left;font-size:13.5px;vertical-align:top}
 th{background:#eef1f5} tr:hover td{background:#f7fafd}
 .ok{color:#0a7d38}.bad{color:#c0392b}.muted{color:#888}
 button{background:#2d6cdf;color:#fff;border:0;border-radius:4px;padding:5px 11px;cursor:pointer;font-size:13px}
 button.warn{background:#c0392b} button.plain{background:#7c8aa0}
 input,select,textarea{padding:6px;border:1px solid #c8d0da;border-radius:4px;font-size:13.5px}
 .cards{display:flex;gap:14px;margin-bottom:18px;flex-wrap:wrap}
 .card{background:#fff;box-shadow:0 1px 2px #0002;border-radius:6px;padding:14px 20px;min-width:130px}
 .card .n{font-size:26px;font-weight:600}.card .t{color:#778;font-size:12.5px}
 #login{max-width:320px;margin:90px auto;background:#fff;padding:26px;border-radius:8px;box-shadow:0 2px 8px #0003;display:flex;flex-direction:column;gap:10px}
 dialog{border:0;border-radius:8px;box-shadow:0 4px 20px #0005;padding:20px;min-width:520px}
 dialog label{display:block;margin:8px 0 2px;font-size:12.5px;color:#556}
 dialog input,dialog select,dialog textarea{width:100%;box-sizing:border-box}
 .row{display:flex;gap:10px}.row>*{flex:1}
 pre{white-space:pre-wrap;background:#0e1620;color:#d7e3ef;padding:10px;border-radius:6px;max-height:300px;overflow:auto}
 .bar{display:flex;gap:8px;margin-bottom:12px;align-items:center;flex-wrap:wrap}
 /* popover: joins the browser top layer so toasts paint above open
    showModal() dialogs (a plain z-index never can) */
 #toasts{position:fixed;inset:auto 14px auto auto;top:14px;margin:0;padding:0;
  border:0;background:transparent;overflow:visible;
  display:flex;flex-direction:column;gap:8px}
 .toast{padding:9px 14px;border-radius:6px;color:#fff;box-shadow:0 2px 8px #0004;
  font-size:13.5px;max-width:340px;animation:fadein .15s}
 .toast.ok{background:#0a7d38}.toast.err{background:#c0392b}
 @keyframes fadein{from{opacity:0;transform:translateY(-6px)}to{opacity:1}}
</style></head><body>
<header><b>cronsun-tpu</b>
 <a data-v=dash></a><a data-v=jobs></a><a data-v=nodes></a>
 <a data-v=groups></a><a data-v=logs></a><a data-v=exec></a>
 <a data-v=accounts id=nav-acc style="display:none"></a>
 <span style="flex:1"></span><a data-v=profile id=who class=muted></a>
 <a id=langbtn title="language"></a><a id=logout></a>
</header>
<main id=main></main>
<div id=toasts popover=manual></div>
<script>
const $=s=>document.querySelector(s);
// non-blocking notifications (the reference's Messager component)
function toast(msg,ok){const c=$('#toasts');const d=document.createElement('div');
 d.className='toast '+(ok?'ok':'err');d.textContent=String(msg);
 c.appendChild(d);try{c.showPopover()}catch(e){}
 setTimeout(()=>{d.remove();if(!c.children.length){try{c.hidePopover()}catch(e){}}},
  ok?2500:6000)}
// ---- i18n (reference: web/ui/src/i18n/ en + zh-CN) ----
const L={en:{
 dash:'Dashboard',jobs:'Jobs',nodes:'Nodes',groups:'Groups',logs:'Logs',
 exec:'Executing',accounts:'Accounts',logout:'logout',signin:'Sign in',
 email:'email',password:'password',loginBtn:'Login',
 cJobs:'jobs',cAlive:'nodes alive',cExecs:'executions',cOk:'succeeded',cFail:'failed',
 daily:'Daily',day:'day',total:'total',success:'success',failed:'failed',
 newJob:'+ New job',name:'name',group:'group',command:'command',kind:'kind',
 timers:'timers',status:'status',edit:'edit',del:'del',run:'run',
 pause:'pause',resume:'resume',paused:'paused',active:'active',
 hostname:'hostname',version:'version',upSince:'up since',connected:'connected',down:'down',
 newGroup:'+ New group',nodesCol:'nodes',
 failedOnly:'failed only',records:'records',job:'job',node:'node',begin:'begin',
 secs:'secs',output:'output',since:'since',nothingRunning:'nothing running',
 newAccount:'+ New account',role:'role',builtIn:'built-in',enabled:'enabled',banned:'banned',
 admin:'Administrator',dev:'Developer',
 profile:'Profile',curPw:'current password',newPw:'new password',
 repPw:'repeat new password',changePw:'Change password',
 pwDiffer:'passwords differ',pwChanged:'password changed',
 editT:'Edit',newT:'New',account:'account',save:'Save',cancel:'Cancel',
 keepEmpty:'(leave empty to keep)',
 kCommon:'Common (all eligible nodes)',kAlone:'Alone (exactly one)',
 kInterval:'Interval (one per interval)',user:'user',timeoutS:'timeout s',
 retry:'retry',parallels:'parallels',
 jitterS:'jitter s (0-300, smears herd)',
 cronTimer:'cron timer (sec min hour dom month dow)',
 nodeIds:'node ids (comma)',groupIds:'group ids',excludeNodes:'exclude nodes',
 delJobQ:'delete job?',delGroupQ:'delete group?',dispatched:'dispatched',
 allNodes:'all eligible nodes',
 addTimer:'+ timer',removeTimer:'remove',timerN:'timer',
 fltName:'name contains',fltNode:'node',fltFrom:'from',fltTo:'to',
 apply:'Apply',clearF:'Clear',
 planner:'Planner',instance:'instance',leaderCol:'leader',
 queueDepth:'queue',overflow:'overflow',watchLoss:'watch loss',
},zh:{
 dash:'仪表盘',jobs:'任务',nodes:'节点',groups:'节点分组',logs:'执行日志',
 exec:'正在执行',accounts:'账户',logout:'退出',signin:'登录',
 email:'邮箱',password:'密码',loginBtn:'登录',
 cJobs:'任务数',cAlive:'在线节点',cExecs:'执行次数',cOk:'成功',cFail:'失败',
 daily:'每日统计',day:'日期',total:'总数',success:'成功',failed:'失败',
 newJob:'+ 新建任务',name:'名称',group:'分组',command:'命令',kind:'类型',
 timers:'定时器',status:'状态',edit:'编辑',del:'删除',run:'执行',
 pause:'暂停',resume:'恢复',paused:'已暂停',active:'启用',
 hostname:'主机名',version:'版本',upSince:'启动时间',connected:'在线',down:'离线',
 newGroup:'+ 新建分组',nodesCol:'节点',
 failedOnly:'只看失败',records:'条记录',job:'任务',node:'节点',begin:'开始时间',
 secs:'耗时(秒)',output:'输出',since:'开始于',nothingRunning:'没有正在执行的任务',
 newAccount:'+ 新建账户',role:'角色',builtIn:'内置',enabled:'启用',banned:'禁用',
 admin:'管理员',dev:'开发者',
 profile:'个人资料',curPw:'当前密码',newPw:'新密码',
 repPw:'重复新密码',changePw:'修改密码',
 pwDiffer:'两次输入的密码不一致',pwChanged:'密码已修改',
 editT:'编辑',newT:'新建',account:'账户',save:'保存',cancel:'取消',
 keepEmpty:'（留空保持不变）',
 kCommon:'普通（所有可选节点执行）',kAlone:'单机（只在一个节点执行）',
 kInterval:'间隔（每个间隔一次）',user:'用户',timeoutS:'超时(秒)',
 retry:'重试次数',parallels:'并发上限',
 jitterS:'抖动秒数（0-300，打散同秒任务）',
 cronTimer:'cron 定时器（秒 分 时 日 月 周）',
 nodeIds:'节点 ID（逗号分隔）',groupIds:'分组 ID',excludeNodes:'排除节点',
 delJobQ:'确定删除该任务？',delGroupQ:'确定删除该分组？',dispatched:'已派发',
 allNodes:'所有可选节点',
 addTimer:'+ 定时器',removeTimer:'删除',timerN:'定时器',
 fltName:'名称包含',fltNode:'节点',fltFrom:'开始',fltTo:'结束',
 apply:'筛选',clearF:'清除',
 planner:'调度器',instance:'实例',leaderCol:'主节点',
 queueDepth:'队列',overflow:'溢出',watchLoss:'监听丢失',
}};
let lang=localStorage.lang||'en';
const t=k=>(L[lang]&&L[lang][k])||L.en[k]||k;
function chrome(){document.querySelectorAll('header a[data-v]').forEach(a=>{
  if(a.id!=='who')a.textContent=t(a.dataset.v)});
 $('#langbtn').textContent=lang==='en'?'中文':'EN';
 $('#logout').textContent=t('logout')}
$('#langbtn').onclick=()=>{lang=lang==='en'?'zh':'en';localStorage.lang=lang;
 chrome();render[view]?nav(view):login()};
// ---- plumbing ----
const api=async(m,p,b)=>{const r=await fetch(p,{method:m,headers:{'Content-Type':'application/json'},
  body:b?JSON.stringify(b):undefined});const d=await r.json().catch(()=>({}));
  if(r.status===401){login();throw 'auth'}if(!r.ok)throw (d.error||r.status);return d};
const esc=s=>String(s??'').replace(/[&<>"]/g,c=>({'&':'&amp;','<':'&lt;','>':'&gt;','"':'&quot;'}[c]));
const ts=t=>t?new Date(t*1000).toLocaleString():'';
let view='dash',me={};
function login(){$('#main').innerHTML=`<form id=login>
 <b>${t('signin')}</b><input id=em placeholder="${t('email')}" value="admin@admin.com">
 <input id=pw type=password placeholder="${t('password')}" value="admin">
 <button>${t('loginBtn')}</button><span id=err class=bad></span></form>`;
 $('#login').onsubmit=async e=>{e.preventDefault();try{
  const d=await api('POST','/v1/session',{email:$('#em').value,password:$('#pw').value});
  me=d;$('#who').textContent=d.email;$('#nav-acc').style.display=d.role===1?'':'none';
  nav(view)}catch(x){$('#err').textContent=x}}}
$('#logout').onclick=async()=>{await api('DELETE','/v1/session');login()};
document.querySelectorAll('header a[data-v]').forEach(a=>a.onclick=()=>nav(a.dataset.v));
function nav(v){view=v;document.querySelectorAll('header a[data-v]').forEach(a=>
 a.classList.toggle('active',a.dataset.v===v));render[v]().catch(e=>{if(e!=='auth')$('#main').innerHTML='<p class=bad>'+esc(e)+'</p>'})}
const render={
 async dash(){const o=await api('GET','/v1/info/overview');
  const sch=Object.entries(o.schedulers||{});
  $('#main').innerHTML=`<div class=cards>
   <div class=card><div class=n>${o.totalJobs}</div><div class=t>${t('cJobs')}</div></div>
   <div class=card><div class=n>${o.nodeAlived}</div><div class=t>${t('cAlive')}</div></div>
   <div class=card><div class=n>${o.jobExecuted.total}</div><div class=t>${t('cExecs')}</div></div>
   <div class=card><div class=n class=ok>${o.jobExecuted.successed}</div><div class=t>${t('cOk')}</div></div>
   <div class=card><div class=n class=bad>${o.jobExecuted.failed}</div><div class=t>${t('cFail')}</div></div></div>
  ${sch.length?`<h3>${t('planner')}</h3><table>
   <tr><th>${t('instance')}</th><th>${t('leaderCol')}</th><th>tick p50/p99 (ms)</th><th>${t('dispatched')}</th><th>${t('queueDepth')}</th><th>${t('overflow')}</th><th>${t('watchLoss')}</th></tr>
   ${sch.map(([id,s])=>`<tr><td>${esc(id)}</td>
    <td>${s.is_leader?`<span class=ok>✓</span>`:`<span class=muted>standby</span>`}</td>
    <td>${esc(s.tick_p50_ms)} / ${esc(s.tick_p99_ms)}</td><td>${esc(s.dispatches_total)}</td>
    <td>${esc(s.dispatch_queue_depth)}</td><td>${esc(s.overflow_drops_total)}</td>
    <td>${esc(s.watch_losses_total)}</td></tr>`).join('')}</table>`:''}
  <h3>${t('daily')}</h3><table><tr><th>${t('day')}</th><th>${t('total')}</th><th>${t('success')}</th><th>${t('failed')}</th></tr>
  ${o.jobExecutedDaily.map(d=>`<tr><td>${d.day}</td><td>${d.total}</td><td class=ok>${d.successed}</td><td class=bad>${d.failed}</td></tr>`).join('')}</table>`},
 async jobs(){const js=await api('GET','/v1/jobs');window._jobs=js;
  // row actions reference rows by index (never interpolate user-controlled
  // ids/groups into JS-string context: a quote in a group name was stored XSS)
  $('#main').innerHTML=`<div class=bar><button onclick="editJob()">${t('newJob')}</button></div>
  <table><tr><th>${t('name')}</th><th>${t('group')}</th><th>${t('command')}</th><th>${t('kind')}</th><th>${t('timers')}</th><th>${t('status')}</th><th></th></tr>
  ${js.map((j,i)=>`<tr><td>${esc(j.name)}</td><td>${esc(j.group)}</td><td><code>${esc(j.command)}</code></td>
   <td>${['Common','Alone','Interval'][j.kind]||j.kind}</td>
   <td>${(j.rules||[]).map(r=>esc(r.timer)).join('<br>')}${j.jitter?`<br><span class=muted>±${+j.jitter}s</span>`:''}</td>
   <td>${j.pause?`<span class=muted>${t('paused')}</span>`:`<span class=ok>${t('active')}</span>`}</td>
   <td><button class=plain onclick="editJob(_jobs[${i}])">${t('edit')}</button>
    <button class=plain onclick="toggleJob(${i})">${j.pause?t('resume'):t('pause')}</button>
    <button onclick="runNow(${i})">${t('run')}</button>
    <button class=warn onclick="delJob(${i})">${t('del')}</button></td></tr>`).join('')}</table>`},
 async nodes(){const ns=await api('GET','/v1/nodes');
  $('#main').innerHTML=`<table><tr><th>id</th><th>${t('hostname')}</th><th>pid</th><th>${t('version')}</th><th>${t('upSince')}</th><th>${t('status')}</th></tr>
  ${ns.map(n=>`<tr><td>${esc(n.id)}</td><td>${esc(n.hostname)}</td><td>${n.pid}</td><td>${esc(n.version)}</td>
   <td>${ts(n.up_ts)}</td><td>${n.connected?`<span class=ok>${t('connected')}</span>`:`<span class=bad>${t('down')}</span>`}</td></tr>`).join('')}</table>`},
 async groups(){const gs=await api('GET','/v1/node/groups');window._groups=gs;
  $('#main').innerHTML=`<div class=bar><button onclick="editGroup()">${t('newGroup')}</button></div>
  <table><tr><th>id</th><th>${t('name')}</th><th>${t('nodesCol')}</th><th></th></tr>
  ${gs.map((g,i)=>`<tr><td>${esc(g.id)}</td><td>${esc(g.name)}</td><td>${(g.nids||[]).map(esc).join(', ')}</td>
   <td><button class=plain onclick="editGroup(_groups[${i}])">${t('edit')}</button>
   <button class=warn onclick="delGroup(${i})">${t('del')}</button></td></tr>`).join('')}</table>`},
 async logs(){
  // filter state persists across renders (reference Log.vue filters:
  // node / name regex / time window / failedOnly, web/job_log.go:18-113)
  const F=window._logF=window._logF||{};
  const page=window._logPage||1,PS=50;
  const q=[`pageSize=${PS}`,`page=${page}`];
  if(F.failed)q.push('failedOnly=true');
  if(F.node)q.push('node='+encodeURIComponent(F.node));
  if(F.names)q.push('names='+encodeURIComponent(F.names));
  if(F.begin)q.push('begin='+(new Date(F.begin).getTime()/1000));
  if(F.end)q.push('end='+(new Date(F.end).getTime()/1000));
  const d=await api('GET','/v1/logs?'+q.join('&'));
  const pages=Math.max(1,Math.ceil(d.total/PS));
  $('#main').innerHTML=`<div class=bar>
   <input id=fn placeholder="${t('fltName')}" value="${esc(F.names||'')}" style="width:130px">
   <input id=fd placeholder="${t('fltNode')}" value="${esc(F.node||'')}" style="width:110px">
   <label class=muted>${t('fltFrom')}</label><input id=fb type=datetime-local value="${esc(F.begin||'')}">
   <label class=muted>${t('fltTo')}</label><input id=fe type=datetime-local value="${esc(F.end||'')}">
   <label><input type=checkbox id=flt ${F.failed?'checked':''}> ${t('failedOnly')}</label>
   <button id=fapply>${t('apply')}</button><button class=plain id=fclear>${t('clearF')}</button>
   <span class=muted>${d.total} ${t('records')}</span><span style="flex:1"></span>
   <button class=plain ${page<=1?'disabled':''} onclick="window._logPage=${page-1};nav('logs')">‹</button>
   <span class=muted>${page} / ${pages}</span>
   <button class=plain ${page>=pages?'disabled':''} onclick="window._logPage=${page+1};nav('logs')">›</button></div>
  <table><tr><th>${t('job')}</th><th>${t('node')}</th><th>${t('begin')}</th><th>${t('secs')}</th><th>ok</th><th>${t('output')}</th></tr>
  ${d.list.map(l=>`<tr style=cursor:pointer onclick="logDetail(${l.id})"><td>${esc(l.name)}</td><td>${esc(l.node)}</td><td>${ts(l.beginTime)}</td>
   <td>${(l.endTime-l.beginTime).toFixed(1)}</td>
   <td>${l.success?'<span class=ok>✓</span>':'<span class=bad>✗</span>'}</td>
   <td><code>${esc((l.output||'').slice(0,160))}</code></td></tr>`).join('')}</table>`;
  $('#fapply').onclick=()=>{window._logF={names:$('#fn').value,node:$('#fd').value,
   begin:$('#fb').value,end:$('#fe').value,failed:$('#flt').checked};
   window._logPage=1;nav('logs')};
  $('#fclear').onclick=()=>{window._logF={};window._logPage=1;nav('logs')}},
 async exec(){const xs=await api('GET','/v1/job/executing');
  $('#main').innerHTML=`<table><tr><th>${t('node')}</th><th>${t('group')}</th><th>${t('job')}</th><th>pid</th><th>${t('since')}</th></tr>
  ${xs.map(x=>`<tr><td>${esc(x.node)}</td><td>${esc(x.group)}</td><td>${esc(x.jobId)}</td>
   <td>${esc(x.pid)}</td><td>${ts(x.time)}</td></tr>`).join('')||`<tr><td colspan=5 class=muted>${t('nothingRunning')}</td></tr>`}</table>`},
 async accounts(){const as=await api('GET','/v1/admin/accounts');window._accts=as;
  $('#main').innerHTML=`<div class=bar><button onclick="editAccount()">${t('newAccount')}</button></div>
  <table><tr><th>${t('email')}</th><th>${t('role')}</th><th>${t('status')}</th><th></th></tr>
  ${as.map((a,i)=>`<tr><td>${esc(a.email)}${a.unchangeable?` <span class=muted>(${t('builtIn')})</span>`:''}</td>
   <td>${a.role===1?t('admin'):t('dev')}</td>
   <td>${a.status===1?`<span class=ok>${t('enabled')}</span>`:`<span class=bad>${t('banned')}</span>`}</td>
   <td><button class=plain onclick="editAccount(_accts[${i}])">${t('edit')}</button></td></tr>`).join('')}</table>`},
 async profile(){
  $('#main').innerHTML=`<h3>${t('profile')} — ${esc(me.email||'')}</h3>
  <form id=pf style="max-width:340px;display:flex;flex-direction:column;gap:8px;background:#fff;padding:18px;border-radius:8px;box-shadow:0 1px 2px #0002">
   <label>${t('curPw')}</label><input id=po type=password>
   <label>${t('newPw')}</label><input id=pn type=password>
   <label>${t('repPw')}</label><input id=pn2 type=password>
   <button>${t('changePw')}</button><span id=pmsg></span></form>`;
  $('#pf').onsubmit=async e=>{e.preventDefault();const m=$('#pmsg');
   if($('#pn').value!==$('#pn2').value){m.className='bad';m.textContent=t('pwDiffer');return}
   try{await api('POST','/v1/user/setpwd',{password:$('#po').value,newPassword:$('#pn').value});
    m.className='ok';m.textContent=t('pwChanged')}catch(x){m.className='bad';m.textContent=x}}},
};
window.editAccount=(a)=>{a=a||{};
 document.body.insertAdjacentHTML('beforeend',`<dialog id=dlg><form method=dialog>
  <b>${a.email?t('editT'):t('newT')} ${t('account')}</b>
  <label>${t('email')}</label><input id=ae value="${esc(a.email||'')}" ${a.email?'disabled':''}>
  <div class=row><div><label>${t('role')}</label><select id=ar>
    <option value=2 ${a.role!==1?'selected':''}>${t('dev')}</option>
    <option value=1 ${a.role===1?'selected':''}>${t('admin')}</option></select></div>
  <div><label>${t('status')}</label><select id=as_>
    <option value=1 ${a.status!==0?'selected':''}>${t('enabled')}</option>
    <option value=0 ${a.status===0?'selected':''}>${t('banned')}</option></select></div></div>
  <label>${t('password')} ${a.email?t('keepEmpty'):''}</label><input id=ap type=password>
  <div class=bar style="margin-top:14px"><button id=sv>${t('save')}</button><button class=plain>${t('cancel')}</button></div>
 </form></dialog>`);const dlg=$('#dlg');dlg.showModal();dlg.onclose=()=>dlg.remove();
 $('#sv').onclick=async e=>{e.preventDefault();try{
  const body={email:a.email||$('#ae').value,role:+$('#ar').value,status:+$('#as_').value};
  if($('#ap').value)body.password=$('#ap').value;
  await api(a.email?'POST':'PUT','/v1/admin/account',body);
  dlg.close();nav('accounts')}catch(x){toast(x)}}};
window.logDetail=async id=>{const l=await api('GET','/v1/log/'+id);
 document.body.insertAdjacentHTML('beforeend',`<dialog id=dlg>
  <b>${esc(l.name)}</b> <span class=muted>@ ${esc(l.node)} · ${ts(l.beginTime)} · ${(l.endTime-l.beginTime).toFixed(2)}s ·
  ${l.success?`<span class=ok>✓</span>`:`<span class=bad>✗</span>`}</span>
  <p><code>${esc(l.command)}</code></p><pre>${esc(l.output||'')}</pre>
  <div class=bar style="margin-top:10px"><form method=dialog><button class=plain>${t('cancel')}</button></form></div>
 </dialog>`);const dlg=$('#dlg');dlg.showModal();dlg.onclose=()=>dlg.remove()};
window.toggleJob=async i=>{const j=_jobs[i];
 await api('POST',`/v1/job/${encodeURIComponent(j.group)}-${encodeURIComponent(j.id)}`,{pause:!j.pause});nav('jobs')};
window.runNow=async i=>{const j=_jobs[i],
 key=`${encodeURIComponent(j.group)}-${encodeURIComponent(j.id)}`;
 const ns=await api('GET',`/v1/job/${key}/nodes`);
 document.body.insertAdjacentHTML('beforeend',`<dialog id=dlg>
  <b>${t('run')}</b>
  <label>${t('node')}</label><select id=xn><option value="">${t('allNodes')}</option>
  ${ns.map(n=>`<option>${esc(n)}</option>`).join('')}</select>
  <div class=bar style="margin-top:14px"><button id=sv>${t('run')}</button>
  <form method=dialog style=display:inline><button class=plain>${t('cancel')}</button></form></div>
 </dialog>`);const dlg=$('#dlg');dlg.showModal();dlg.onclose=()=>dlg.remove();
 $('#sv').onclick=async e=>{e.preventDefault();try{
  await api('PUT',`/v1/job/${key}/execute?node=`+encodeURIComponent($('#xn').value));
  dlg.close();toast(t('dispatched'),true)}catch(x){toast(x)}}};
window.delJob=async i=>{const j=_jobs[i];if(confirm(t('delJobQ'))){
 await api('DELETE',`/v1/job/${encodeURIComponent(j.group)}-${encodeURIComponent(j.id)}`);nav('jobs')}};
window.delGroup=async i=>{const g=_groups[i];if(confirm(t('delGroupQ'))){
 await api('DELETE','/v1/node/group/'+encodeURIComponent(g.id));nav('groups')}};
// Multi-rule job editor (reference JobEditRule.vue edits a LIST of rules per
// job, web/ui/src/components/JobEdit.vue): every rule renders as its own
// timer/nids/gids/exclude row with add/remove; saving collects all rows —
// editing a >=2-rule job must never drop rules.
window.editJob=(j)=>{j=j||{};
 const rules=(j.rules&&j.rules.length?j.rules:[{}]).map(r=>({...r}));
 const ruleRow=(r,k)=>`<fieldset style="border:1px solid #dde;border-radius:6px;margin:8px 0;padding:4px 10px 10px">
  <legend class=muted style="font-size:12px">${t('timerN')} ${k+1}
   <a style="cursor:pointer;color:#c0392b" data-rm=${k}>✕ ${t('removeTimer')}</a></legend>
  <label>${t('cronTimer')}</label><input data-rt=${k} value="${esc(r.timer||'0 */5 * * * *')}">
  <div class=row><div><label>${t('nodeIds')}</label><input data-rn=${k} value="${esc((r.nids||[]).join(','))}"></div>
  <div><label>${t('groupIds')}</label><input data-rg=${k} value="${esc((r.gids||[]).join(','))}"></div>
  <div><label>${t('excludeNodes')}</label><input data-rx=${k} value="${esc((r.exclude_nids||[]).join(','))}"></div></div>
 </fieldset>`;
 document.body.insertAdjacentHTML('beforeend',`<dialog id=dlg><form method=dialog>
  <b>${j.id?t('editT'):t('newT')} ${t('job')}</b>
  <div class=row><div><label>${t('name')}</label><input id=jn value="${esc(j.name||'')}"></div>
  <div><label>${t('group')}</label><input id=jg value="${esc(j.group||'default')}"></div></div>
  <label>${t('command')}</label><textarea id=jc rows=2>${esc(j.command||'')}</textarea>
  <div class=row><div><label>${t('kind')}</label><select id=jk>
    <option value=0 ${j.kind==0?'selected':''}>${t('kCommon')}</option>
    <option value=1 ${j.kind==1?'selected':''}>${t('kAlone')}</option>
    <option value=2 ${j.kind==2?'selected':''}>${t('kInterval')}</option></select></div>
  <div><label>${t('user')}</label><input id=ju value="${esc(j.user||'')}"></div></div>
  <div class=row><div><label>${t('timeoutS')}</label><input id=jt type=number value="${j.timeout||0}"></div>
  <div><label>${t('retry')}</label><input id=jr type=number value="${j.retry||0}"></div>
  <div><label>${t('parallels')}</label><input id=jp type=number value="${j.parallels||0}"></div>
  <div><label>${t('jitterS')}</label><input id=jj type=number min=0 max=300 value="${j.jitter||0}"></div></div>
  <div id=rules></div>
  <button class=plain id=addr style="margin-top:4px">${t('addTimer')}</button>
  <div class=bar style="margin-top:14px"><button id=sv>${t('save')}</button><button class=plain>${t('cancel')}</button></div>
 </form></dialog>`);const dlg=$('#dlg');dlg.showModal();dlg.onclose=()=>dlg.remove();
 const csv=v=>v.split(',').map(s=>s.trim()).filter(Boolean);
 const harvest=()=>{rules.forEach((r,k)=>{const f=s=>dlg.querySelector(`[data-${s}="${k}"]`);
  if(!f('rt'))return;
  r.timer=f('rt').value;r.nids=csv(f('rn').value);
  r.gids=csv(f('rg').value);r.exclude_nids=csv(f('rx').value)})};
 const paint=()=>{ $('#rules').innerHTML=rules.map(ruleRow).join('');
  dlg.querySelectorAll('[data-rm]').forEach(a=>a.onclick=e=>{e.preventDefault();
   harvest();rules.splice(+a.dataset.rm,1);if(!rules.length)rules.push({});paint()})};
 paint();
 $('#addr').onclick=e=>{e.preventDefault();harvest();rules.push({});paint()};
 $('#sv').onclick=async e=>{e.preventDefault();harvest();
  try{await api('PUT','/v1/job',{id:j.id,name:$('#jn').value,group:$('#jg').value,oldGroup:j.group,
   command:$('#jc').value,kind:+$('#jk').value,user:$('#ju').value,timeout:+$('#jt').value,
   retry:+$('#jr').value,parallels:+$('#jp').value,jitter:+$('#jj').value,pause:!!j.pause,
   rules:rules.map(r=>({id:r.id,timer:r.timer,nids:r.nids||[],gids:r.gids||[],
           exclude_nids:r.exclude_nids||[]}))});dlg.close();nav('jobs')}catch(x){toast(x)}}};
window.editGroup=(g)=>{g=g||{};
 document.body.insertAdjacentHTML('beforeend',`<dialog id=dlg><form method=dialog>
  <b>${g.id?t('editT'):t('newT')} ${t('group')}</b>
  <label>${t('name')}</label><input id=gn value="${esc(g.name||'')}">
  <label>${t('nodeIds')}</label><input id=gm value="${esc((g.nids||[]).join(','))}">
  <div class=bar style="margin-top:14px"><button id=sv>${t('save')}</button><button class=plain>${t('cancel')}</button></div>
 </form></dialog>`);const dlg=$('#dlg');dlg.showModal();dlg.onclose=()=>dlg.remove();
 $('#sv').onclick=async e=>{e.preventDefault();try{
  await api('PUT','/v1/node/group',{id:g.id,name:$('#gn').value,
   nids:$('#gm').value.split(',').map(s=>s.trim()).filter(Boolean)});dlg.close();nav('groups')}catch(x){toast(x)}}};
chrome();
api('GET','/v1/session/me').then(d=>{me=d;$('#who').textContent=d.email;
 $('#nav-acc').style.display=d.role===1?'':'none';nav('dash')}).catch(()=>login());
</script></body></html>
"""
