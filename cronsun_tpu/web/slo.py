"""SLO engine: multi-window multi-burn-rate alerting on the web tier.

Declarative SLO records (core.models.SloSpec) live under the ``slo/``
keyspace family.  Each evaluation tick the engine

1. lists the specs,
2. scrapes the per-scope execution counters every agent publishes in
   its leased metrics snapshot (``metrics/node/<id>`` -> ``"slo"``:
   {scope: {count, fail, sum_ms, buckets}}) and SUMS them fleet-wide
   (fixed bucket bounds make the histograms addable — dead agents'
   numbers expire with their lease),
3. appends the sums to a bounded per-scope sample ring (~6h), and
4. computes burn rates over the four canonical windows.

Burn rate = bad_fraction / (1 - target), where an execution is bad
when it failed or (``latency_ms`` > 0) ran longer than the threshold —
counted from the histogram buckets, so the threshold snaps to a bucket
bound (pick thresholds from trace.BUCKETS_MS).

Alerting follows the Google SRE-workbook ladder: a FAST page when the
burn exceeds 14.4 over BOTH the 5m and 1h windows (2% of a 30-day
budget in one hour), a SLOW page at 6 over BOTH 30m and 6h.  Requiring
both windows keeps a brief spike from paging while still catching a
sustained burn within minutes.  Transitions into alert write ONE
rate-limited notice key through the noticer (the breaker-paging
pattern); recovery clears the state without paging.

``cronsun_slo_burn_rate{slo=,window=}`` and
``cronsun_slo_alert{slo=,severity=}`` render at /v1/metrics.
"""

from __future__ import annotations

import bisect
import json
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import log, trace as _trace
from ..core import Keyspace
from ..core.models import SloSpec

# (severity, short window, long window, burn threshold)
WINDOWS = (("fast", "5m", "1h", 14.4),
           ("slow", "30m", "6h", 6.0))
WINDOW_LABELS = ("5m", "30m", "1h", "6h")
_WINDOW_S = {"5m": 300.0, "30m": 1800.0, "1h": 3600.0, "6h": 21600.0}


class SloEngine:
    def __init__(self, store, ks: Optional[Keyspace] = None,
                 interval_s: float = 15.0,
                 notice_interval_s: float = 300.0,
                 clock: Callable[[], float] = time.time):
        self.store = store
        self.ks = ks or Keyspace()
        self.interval_s = max(1.0, float(interval_s))
        self.notice_interval_s = notice_interval_s
        self.clock = clock
        self._mu = threading.Lock()
        # scope -> [(ts, count, fail, buckets tuple)] sample ring
        self._ring: Dict[str, List[tuple]] = {}
        self._ring_keep = 21600.0 + 4 * self.interval_s
        # slo name -> {"burn": {window: x}, "alert": ""|"fast"|"slow",
        #              "since": ts}
        self._state: Dict[str, dict] = {}
        self._last_sums: Optional[Dict[str, list]] = None
        self._last_notice: Dict[str, float] = {}
        self.stats = {"slo_evals_total": 0, "slo_alerts_total": 0,
                      "slo_notices_total": 0, "slo_recoveries_total": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ---- spec + scrape plumbing -----------------------------------------

    def specs(self) -> List[SloSpec]:
        out = []
        for kv in self.store.get_prefix(self.ks.slo):
            try:
                spec = SloSpec.from_json(kv.value)
                spec.validate()
                out.append(spec)
            except Exception:  # noqa: BLE001 — skip malformed records
                continue
        return out

    def _scrape(self) -> Dict[str, list]:
        """Sum the per-scope SLO counters across every live agent
        snapshot: scope -> [count, fail, sum_ms, buckets, fbuckets]
        (fbuckets = failure-latency histogram; a legacy agent without
        it sums as zeros and _bad_good falls back conservatively)."""
        sums: Dict[str, list] = {}

        def add(tb: list, b) -> None:
            if len(tb) < len(b):
                tb.extend([0] * (len(b) - len(tb)))
            for i, v in enumerate(b):
                tb[i] += int(v)

        for kv in self.store.get_prefix(self.ks.metrics + "node/"):
            try:
                snap = json.loads(kv.value)
            except json.JSONDecodeError:
                continue
            slo = snap.get("slo")
            if not isinstance(slo, dict):
                continue
            for scope, ent in slo.items():
                if not isinstance(ent, dict):
                    continue
                tgt = sums.setdefault(scope, [0, 0, 0.0, [], []])
                tgt[0] += int(ent.get("count", 0))
                tgt[1] += int(ent.get("fail", 0))
                tgt[2] += float(ent.get("sum_ms", 0.0))
                add(tgt[3], ent.get("buckets") or [])
                add(tgt[4], ent.get("fbuckets") or [])
        return sums

    # ---- evaluation ------------------------------------------------------

    def tick(self):
        """One evaluation pass (the background loop calls this every
        ``interval_s``; tests drive it directly)."""
        now = self.clock()
        sums = self._scrape()
        with self._mu:
            self._last_sums = sums
            for scope, (count, fail, sum_ms, buckets,
                        fbuckets) in sums.items():
                ring = self._ring.setdefault(scope, [])
                ring.append((now, count, fail, tuple(buckets),
                             tuple(fbuckets)))
                cut = now - self._ring_keep
                while len(ring) > 2 and ring[0][0] < cut:
                    ring.pop(0)
            self.stats["slo_evals_total"] += 1
        specs = self.specs()
        for spec in specs:
            self._eval_spec(spec, now)
        # a DELETED spec must not keep rendering (or alerting) forever:
        # drop engine state for names no longer in the keyspace
        live = {s.name for s in specs}
        with self._mu:
            for name in [n for n in self._state if n not in live]:
                del self._state[name]
                self._last_notice.pop(name, None)

    def _sample_at(self, ring: List[tuple], ts: float):
        """Newest sample at or before ``ts`` — or the OLDEST sample
        (partial-window evaluation: a burn must be visible before a
        full 6h of history exists)."""
        prev = ring[0]
        for s in ring:
            if s[0] > ts:
                break
            prev = s
        return prev

    def _bad_good(self, sample, spec: SloSpec):
        """(bad, total) cumulative at one sample for one spec.  bad =
        failed OR slower than the latency threshold.  With failure
        buckets the joint is exact: bad = fail + slow successes =
        (count - fast_all) + fast_fail.  Without them (legacy agent
        snapshots sum to all-zero fbuckets while fail > 0) the clamp
        assumes every failure was slow — the conservative lower bound
        the engine always used."""
        _ts, count, fail, buckets, fbuckets = sample
        bad = fail
        if spec.latency_ms > 0 and buckets:
            k = bisect.bisect_right(_trace.BUCKETS_MS, spec.latency_ms)
            fast_all = sum(buckets[:k])
            fast_fail = sum(fbuckets[:k])
            if fail and not any(fbuckets):
                bad += max(0, count - fast_all - fail)
            else:
                bad = max(fail, (count - fast_all) + fast_fail)
        return bad, count

    def burn_rates(self, spec: SloSpec) -> Dict[str, float]:
        """Burn rate per canonical window from the counter deltas."""
        scope = spec.counter_scope
        with self._mu:
            ring = list(self._ring.get(scope) or [])
        out = {}
        if len(ring) < 2:
            return {w: 0.0 for w in WINDOW_LABELS}
        newest = ring[-1]
        nb, nt = self._bad_good(newest, spec)
        for label in WINDOW_LABELS:
            base = self._sample_at(ring[:-1],
                                   newest[0] - _WINDOW_S[label])
            bb, bt = self._bad_good(base, spec)
            total = nt - bt
            bad = max(0, nb - bb)
            frac = (bad / total) if total > 0 else 0.0
            out[label] = round(frac / max(1e-9, 1.0 - spec.target), 3)
        return out

    def _eval_spec(self, spec: SloSpec, now: float):
        burn = self.burn_rates(spec)
        severity = ""
        for label, short_l, long_l, thresh in WINDOWS:
            if burn[short_l] >= thresh and burn[long_l] >= thresh:
                severity = label
                break           # fast outranks slow
        with self._mu:
            st = self._state.setdefault(
                spec.name, {"burn": {}, "alert": "", "since": 0.0,
                            "scope": spec.scope, "target": spec.target,
                            "latency_ms": spec.latency_ms})
            st["burn"] = burn
            st["scope"] = spec.scope
            st["target"] = spec.target
            st["latency_ms"] = spec.latency_ms
            was = st["alert"]
            if severity and not was:
                st["alert"] = severity
                st["since"] = now
                self.stats["slo_alerts_total"] += 1
                fire = True
            elif not severity and was:
                st["alert"] = ""
                st["since"] = now
                self.stats["slo_recoveries_total"] += 1
                fire = False
            else:
                st["alert"] = severity or ""
                fire = False
        if fire:
            self._page(spec, severity, burn, now)

    def _page(self, spec: SloSpec, severity: str, burn: dict,
              now: float):
        """Write ONE rate-limited notice key through the noticer (the
        PR 13 breaker-paging ladder): a flapping SLO pages once per
        ``notice_interval_s``, not once per transition."""
        last = self._last_notice.get(spec.name, 0.0)
        if now - last < self.notice_interval_s:
            return
        self._last_notice[spec.name] = now
        key = self.ks.noticer_key(f"slo-{spec.name}")
        body = json.dumps({
            "subject": f"[cronsun] SLO {spec.name} {severity}-burn "
                       f"alert",
            "body": f"SLO {spec.name} (scope {spec.scope or 'global'}, "
                    f"target {spec.target}"
                    + (f", latency <= {spec.latency_ms}ms"
                       if spec.latency_ms else "")
                    + f") is burning error budget: "
                    f"burn rates 5m={burn['5m']} 1h={burn['1h']} "
                    f"30m={burn['30m']} 6h={burn['6h']}. "
                    "See cronsun_slo_burn_rate at /v1/metrics and "
                    "cronsun-ctl slo show."})
        try:
            self.store.put(key, body)
            self.stats["slo_notices_total"] += 1
        except Exception as e:  # noqa: BLE001 — the gauge is the
            # real-time signal; the page retries on the next interval
            log.warnf("slo notice for %s could not be written: %s",
                      spec.name, e)
            self._last_notice[spec.name] = 0.0

    # ---- surfaces --------------------------------------------------------

    def snapshot(self) -> dict:
        """Current burn rates + alert states for /v1/slo and the
        /v1/metrics gauges."""
        with self._mu:
            states = {name: {"burn": dict(st["burn"]),
                             "alert": st["alert"],
                             "since": st["since"],
                             "scope": st.get("scope", ""),
                             "target": st.get("target", 0.0),
                             "latency_ms": st.get("latency_ms", 0.0)}
                      for name, st in self._state.items()}
            stats = dict(self.stats)
        return {"slos": states, "stats": stats}

    def scrape_sums(self) -> Dict[str, list]:
        """Latest per-scope counter sums (for the exec-latency
        histogram rendering at /v1/metrics): scope -> [count, fail,
        sum_ms, buckets]."""
        with self._mu:
            return {scope: [v[0], v[1], v[2], list(v[3])]
                    for scope, v in (self._last_sums or {}).items()}

    # ---- lifecycle -------------------------------------------------------

    def start(self):
        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick()
                except Exception as e:  # noqa: BLE001 — keep evaluating
                    log.warnf("slo eval failed: %s", e)
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="slo-engine")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=3)
