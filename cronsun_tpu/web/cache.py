"""Revision-vector response cache for the web tier's read endpoints.

A dashboard polls the same handful of shapes (latest view, stat
counters) in a tight loop; the PR 7 ETag path already reads the result
store's revision — scalar for one sink, a per-shard VECTOR for a
sharded one — on every poll.  This cache keys whole responses (and
their per-shard partial results) on that same token:

- revision unchanged and the client sent the ETag  → 304, no body
- revision unchanged, no/stale client ETag         → cached body,
  zero sink reads beyond the revision
- revision CHANGED                                 → recompute ONLY the
  shards whose vector entry moved; unchanged shards' cached partials
  feed the scatter-gather merge unchanged

Soundness: a shard's cached partial is reused only when its CURRENT
revision equals the revision read just before the partial was computed.
Writes racing the compute bump the revision, so the stale-labeled entry
can never satisfy a later lookup — reuse implies no intervening write,
which implies the partial is exact.

``CRONSUN_WEB_CACHE=off`` (or ``ApiServer(cache_enabled=False)``) is
the rollback switch: every poll recomputes, exactly today's behavior.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import List, Optional


def cache_default() -> bool:
    return os.environ.get("CRONSUN_WEB_CACHE", "").lower() not in (
        "off", "0", "false")


class ResponseCache:
    """Bounded LRU of {key -> (revision vector, per-shard partials,
    merged body)} plus the effectiveness counters the bench and
    /v1/metrics read.  Keys carry every request parameter that shapes
    the body, so two filtered views never satisfy each other."""

    def __init__(self, maxsize: int = 256):
        self._maxsize = max(1, maxsize)
        self._lock = threading.Lock()
        self._ent: OrderedDict = OrderedDict()
        self._stats = {
            "etag_304_total": 0,        # If-None-Match matched: no body
            "body_hits_total": 0,       # unchanged vector: cached body
            "shard_reused_total": 0,    # per-shard partials reused
            "shard_recomputed_total": 0,
            "misses_total": 0,          # no entry for the key at all
        }

    def lookup(self, key) -> Optional[dict]:
        with self._lock:
            ent = self._ent.get(key)
            if ent is not None:
                self._ent.move_to_end(key)
            return ent

    def store(self, key, revs: List[int], parts: list, body):
        with self._lock:
            self._ent[key] = {"revs": revs, "parts": parts, "body": body}
            self._ent.move_to_end(key)
            while len(self._ent) > self._maxsize:
                self._ent.popitem(last=False)

    def bump(self, stat: str, n: int = 1):
        with self._lock:
            self._stats[stat] += n

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._stats)
