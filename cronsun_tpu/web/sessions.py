"""Sessions in the coordination store (reference web/session/session.go:53-150:
gob blobs under /cronsun/sess/<key> with an expiration lease; JSON here)."""

from __future__ import annotations

import json
import secrets
from typing import Optional

from ..core import Keyspace
from ..store.memstore import MemStore


class Session(dict):
    @property
    def email(self) -> str:
        return self.get("email", "")

    @property
    def role(self) -> int:
        return int(self.get("role", 0))


class SessionStore:
    def __init__(self, store: MemStore, ks: Optional[Keyspace] = None,
                 ttl: float = 8 * 3600):
        self.store = store
        self.ks = ks or Keyspace()
        self.ttl = ttl

    def create(self, email: str, role: int) -> str:
        sid = secrets.token_hex(16)
        lease = self.store.grant(self.ttl)
        self.store.put(self.ks.sess_key(sid),
                       json.dumps({"email": email, "role": role}),
                       lease=lease)
        return sid

    def get(self, sid: str) -> Optional[Session]:
        """Resolve a session; expiry slides on use (the reference
        re-stores the session after every request, base.go deferred
        todos).  The lease keepalive only fires once the remaining TTL
        drops below half, so hot sessions cost one extra RPC rarely."""
        if not sid:
            return None
        kv = self.store.get(self.ks.sess_key(sid))
        if kv is None:
            return None
        if kv.lease:
            rem = self.store.lease_ttl_remaining(kv.lease)
            if rem is not None and rem < self.ttl / 2:
                self.store.keepalive(kv.lease)
        try:
            return Session(json.loads(kv.value))
        except json.JSONDecodeError:
            return None

    def destroy(self, sid: str):
        self.store.delete(self.ks.sess_key(sid))

    def destroy_email(self, email: str):
        """Force-logout every session of an account (reference
        administrator.go force-logout on edit)."""
        for kv in self.store.get_prefix(self.ks.sess):
            try:
                if json.loads(kv.value).get("email") == email:
                    self.store.delete(kv.key)
            except json.JSONDecodeError:
                continue
