"""Failure notification (reference noticer.go).

Agents put JSON messages under /cronsun/noticer/<node>; a Noticer hosted by
the web process watches the prefix and delivers — by SMTP (connection kept
alive between sends, closed after ``keepalive`` idle seconds,
noticer.go:70-104) or by POSTing to an HTTP API (noticer.go:114-145).
Node-death monitoring (noticer.go:172-200): a DELETE of a node key whose
result-store mirror still says alive means a crash, not a clean shutdown —
that also produces a notice.
"""

from __future__ import annotations

import json
import smtplib
import threading
import time
import urllib.request
from email.mime.text import MIMEText
from typing import Callable, List, Optional

from .core import Keyspace
from .core.backoff import NOTICER
from . import log
from .logsink import JobLogStore
from .store.memstore import DELETE, MemStore, WatchLost


class Notice:
    def __init__(self, subject: str, body: str, to: Optional[List[str]] = None):
        self.subject = subject
        self.body = body
        self.to = to or []


class MailNoticer:
    """SMTP sender with a kept-alive connection."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 default_to: List[str], keepalive: int = 30,
                 use_tls: bool = True):
        self.host, self.port = host, port
        self.user, self.password = user, password
        self.default_to = default_to
        self.keepalive = keepalive
        self.use_tls = use_tls
        self._conn: Optional[smtplib.SMTP] = None
        self._last_send = 0.0
        self._lock = threading.Lock()

    def _connect(self) -> smtplib.SMTP:
        conn = smtplib.SMTP(self.host, self.port, timeout=10)
        if self.use_tls:
            conn.starttls()
        if self.user:
            conn.login(self.user, self.password)
        return conn

    def send(self, notice: Notice):
        to = notice.to or self.default_to
        if not to:
            return
        msg = MIMEText(notice.body)
        msg["Subject"] = notice.subject
        msg["From"] = self.user
        msg["To"] = ", ".join(to)
        with self._lock:
            if self._conn is None:
                self._conn = self._connect()
            try:
                self._conn.sendmail(self.user, to, msg.as_string())
            except smtplib.SMTPException:
                self._conn = self._connect()     # reconnect once
                self._conn.sendmail(self.user, to, msg.as_string())
            self._last_send = time.time()

    def idle_check(self):
        """Close the cached connection after ``keepalive`` idle seconds."""
        with self._lock:
            if self._conn is not None and \
                    time.time() - self._last_send > self.keepalive:
                try:
                    self._conn.quit()
                except smtplib.SMTPException:
                    pass
                self._conn = None


class HttpNoticer:
    """POST the notice as JSON to an HTTP API (noticer.go:114-145)."""

    def __init__(self, url: str):
        self.url = url

    def send(self, notice: Notice):
        payload = json.dumps({"subject": notice.subject, "body": notice.body,
                              "to": notice.to}).encode()
        req = urllib.request.Request(
            self.url, data=payload, method="POST",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10)


class _Pending:
    """A notice awaiting (re)delivery.  ``key`` is the store key deleted
    on success (None for synthesized node-death alerts); ``on_success``
    runs exactly once after the first successful send."""

    def __init__(self, notice: Notice, key: Optional[str],
                 on_success: Optional[Callable[[], None]]):
        self.notice = notice
        self.key = key
        self.on_success = on_success
        self.attempts = 0
        self.next_at = 0.0


class NoticerHost:
    """Watches the noticer prefix + node deaths; fans out to a sender.

    Delivery is durable: the noticer store key is deleted only after a
    successful send (the reference deletes the etcd key after SMTP
    delivery, noticer.go:147-170).  A failed send stays queued with
    exponential backoff (capped at RETRY_CAP seconds), and because the key
    survives, a noticer restart re-lists and re-delivers via resync()."""

    RETRY_CAP = NOTICER.cap     # schedule lives in core.backoff.NOTICER

    def __init__(self, store: MemStore, sink: JobLogStore, sender,
                 ks: Optional[Keyspace] = None):
        self.store = store
        self.sink = sink
        self.sender = sender
        self.ks = ks or Keyspace()
        self._open_watches()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sent: List[Notice] = []     # for introspection/tests
        self._pending: dict = {}         # dedupe-key -> _Pending

    def _open_watches(self):
        self._w_notice = self.store.watch(self.ks.noticer)
        self._w_nodes = self.store.watch(self.ks.node)

    def _alert_node_down(self, nid: str) -> int:
        """Queue the crash alert; the mirror is marked dead only once the
        alert is actually delivered, so a crash of *this* process before
        delivery leaves the mirror alive and the next resync re-alerts.
        The dedupe key stops the level-triggered resync check from
        queueing the same crash twice while delivery is pending."""
        return self._submit(
            Notice(f"[cronsun] node [{nid}] down",
                   f"node {nid} lease expired without clean shutdown"),
            dedupe=f"nodedown/{nid}",
            on_success=lambda: self._mark_down_if_still_gone(nid))

    def _mark_down_if_still_gone(self, nid: str):
        """Delivery can lag crash detection by a long retry outage; if
        the node re-registered meanwhile, leave the mirror alive — a
        wrong dead flag here would swallow the alert for its NEXT real
        crash (both poll and resync gate on mirror alived)."""
        try:
            if self.store.get(self.ks.node_key(nid)) is not None:
                return
            self.sink.set_node_alived(nid, False)
        except Exception as e:  # noqa: BLE001 — can't verify / can't mark:
            # keep the mirror alive; the next resync re-checks (a stale
            # alive flag re-alerts, a wrong dead flag swallows alerts)
            log.warnf("node-down mirror mark for %s skipped: %s", nid, e)

    def poll(self) -> int:
        try:
            return self._poll_once()
        except WatchLost as e:
            log.warnf("noticer watch lost (%s); resynchronizing", e)
            try:
                return self.resync()
            except Exception as e2:  # noqa: BLE001
                log.errorf("noticer resync failed (retrying next poll): %s",
                           e2)
                return 0
        except Exception as e:  # noqa: BLE001 — a transient store/sink
            # outage (e.g. the remote result store briefly unreachable)
            # must not kill the noticer thread: alerts stay queued/keyed
            # and the next poll retries
            log.errorf("noticer poll failed (retrying next poll): %s", e)
            return 0

    def resync(self) -> int:
        """Re-watch and queue any pending notices from a re-list (keys
        are deleted only after successful delivery, so the re-list sees
        everything undelivered; the dedupe key makes re-queueing a
        no-op for notices already awaiting retry).  Node-death events
        inside the lost window are recovered by checking the alived
        mirror against the current node list."""
        for w in (self._w_notice, self._w_nodes):
            try:
                w.close()
            except Exception:   # noqa: BLE001
                pass
        self._open_watches()
        n = 0
        for kv in self.store.get_prefix(self.ks.noticer):
            notice = self._parse(kv.value)
            if notice is not None:
                n += self._submit(notice, key=kv.key)
        # nodes the mirror says are alive but whose lease key vanished
        # during the gap died uncleanly
        live = {kv.key[len(self.ks.node):]
                for kv in self.store.get_prefix(self.ks.node)}
        for mirror in self.sink.get_nodes():
            nid = mirror.get("id")
            if mirror.get("alived") and nid not in live:
                n += self._alert_node_down(nid)
        return n

    def _poll_once(self) -> int:
        n = self._retry_due()
        for ev in self._w_notice.drain():
            if ev.type == DELETE:
                continue
            notice = self._parse(ev.kv.value)
            if notice is not None:
                n += self._submit(notice, key=ev.kv.key)
        for ev in self._w_nodes.drain():
            if ev.type != DELETE:
                continue
            node_id = ev.kv.key[len(self.ks.node):]
            mirror = self.sink.get_node(node_id)
            if mirror and mirror.get("alived"):
                # lease expired but the node never said goodbye: a fault
                # (reference node.go:93-102 ISNodeFault)
                n += self._alert_node_down(node_id)
        return n

    @staticmethod
    def _parse(value: str) -> Optional[Notice]:
        try:
            d = json.loads(value)
        except json.JSONDecodeError:
            return None
        return Notice(d.get("subject", ""), d.get("body", ""), d.get("to"))

    def _submit(self, notice: Notice, key: Optional[str] = None,
                dedupe: Optional[str] = None,
                on_success: Optional[Callable[[], None]] = None) -> int:
        """Attempt delivery now; on failure park in the retry queue.
        A notice already parked under the same key is *replaced*, not
        dropped: agents overwrite one per-node noticer key
        (node/agent.py), so the store itself only retains the latest
        value — delivering the stale parked one and deleting the key
        would silently lose the newer notice."""
        dk = dedupe or key or f"anon/{id(notice)}"
        parked = self._pending.get(dk)
        if parked is not None:
            parked.notice = notice            # latest wins, keep backoff
            return 0
        p = _Pending(notice, key, on_success)
        if self._attempt(p):
            return 1
        self._pending[dk] = p
        return 0

    def _attempt(self, p: _Pending) -> bool:
        try:
            self.sender.send(p.notice)
        except Exception as e:  # noqa: BLE001 — notification must not crash
            p.attempts += 1
            backoff = NOTICER.delay(p.attempts)
            p.next_at = time.time() + backoff
            log.errorf("noticer send failed (attempt %d, retry in %.1fs): %s",
                       p.attempts, backoff, e)
            return False
        self.sent.append(p.notice)
        if p.key is not None:
            try:
                self.store.delete(p.key)
            except Exception as e:  # noqa: BLE001 — redelivery beats loss
                log.warnf("noticer key %r delete failed: %s", p.key, e)
        if p.on_success is not None:
            p.on_success()
        return True

    def _retry_due(self) -> int:
        now = time.time()
        n = 0
        for dk, p in list(self._pending.items()):
            if p.next_at <= now and self._attempt(p):
                self._pending.pop(dk, None)
                n += 1
        return n

    def start(self):
        def run():
            while not self._stop.wait(0.5):
                try:
                    self.poll()
                    if hasattr(self.sender, "idle_check"):
                        self.sender.idle_check()
                except Exception as e:  # noqa: BLE001 — never die silently
                    log.errorf("noticer loop error: %s", e)
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="noticer")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)
