"""Failure notification (reference noticer.go).

Agents put JSON messages under /cronsun/noticer/<node>; a Noticer hosted by
the web process watches the prefix and delivers — by SMTP (connection kept
alive between sends, closed after ``keepalive`` idle seconds,
noticer.go:70-104) or by POSTing to an HTTP API (noticer.go:114-145).
Node-death monitoring (noticer.go:172-200): a DELETE of a node key whose
result-store mirror still says alive means a crash, not a clean shutdown —
that also produces a notice.
"""

from __future__ import annotations

import json
import smtplib
import threading
import time
import urllib.request
from email.mime.text import MIMEText
from typing import Callable, List, Optional

from .core import Keyspace
from . import log
from .logsink import JobLogStore
from .store.memstore import DELETE, MemStore, WatchLost


class Notice:
    def __init__(self, subject: str, body: str, to: Optional[List[str]] = None):
        self.subject = subject
        self.body = body
        self.to = to or []


class MailNoticer:
    """SMTP sender with a kept-alive connection."""

    def __init__(self, host: str, port: int, user: str, password: str,
                 default_to: List[str], keepalive: int = 30,
                 use_tls: bool = True):
        self.host, self.port = host, port
        self.user, self.password = user, password
        self.default_to = default_to
        self.keepalive = keepalive
        self.use_tls = use_tls
        self._conn: Optional[smtplib.SMTP] = None
        self._last_send = 0.0
        self._lock = threading.Lock()

    def _connect(self) -> smtplib.SMTP:
        conn = smtplib.SMTP(self.host, self.port, timeout=10)
        if self.use_tls:
            conn.starttls()
        if self.user:
            conn.login(self.user, self.password)
        return conn

    def send(self, notice: Notice):
        to = notice.to or self.default_to
        if not to:
            return
        msg = MIMEText(notice.body)
        msg["Subject"] = notice.subject
        msg["From"] = self.user
        msg["To"] = ", ".join(to)
        with self._lock:
            if self._conn is None:
                self._conn = self._connect()
            try:
                self._conn.sendmail(self.user, to, msg.as_string())
            except smtplib.SMTPException:
                self._conn = self._connect()     # reconnect once
                self._conn.sendmail(self.user, to, msg.as_string())
            self._last_send = time.time()

    def idle_check(self):
        """Close the cached connection after ``keepalive`` idle seconds."""
        with self._lock:
            if self._conn is not None and \
                    time.time() - self._last_send > self.keepalive:
                try:
                    self._conn.quit()
                except smtplib.SMTPException:
                    pass
                self._conn = None


class HttpNoticer:
    """POST the notice as JSON to an HTTP API (noticer.go:114-145)."""

    def __init__(self, url: str):
        self.url = url

    def send(self, notice: Notice):
        payload = json.dumps({"subject": notice.subject, "body": notice.body,
                              "to": notice.to}).encode()
        req = urllib.request.Request(
            self.url, data=payload, method="POST",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=10)


class NoticerHost:
    """Watches the noticer prefix + node deaths; fans out to a sender."""

    def __init__(self, store: MemStore, sink: JobLogStore, sender,
                 ks: Optional[Keyspace] = None):
        self.store = store
        self.sink = sink
        self.sender = sender
        self.ks = ks or Keyspace()
        self._open_watches()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.sent: List[Notice] = []     # for introspection/tests

    def _open_watches(self):
        self._w_notice = self.store.watch(self.ks.noticer)
        self._w_nodes = self.store.watch(self.ks.node)

    def _alert_node_down(self, nid: str) -> int:
        """Deliver the crash alert and mark the mirror dead so the
        level-triggered resync check cannot re-alert the same crash."""
        n = self._deliver(Notice(
            f"[cronsun] node [{nid}] down",
            f"node {nid} lease expired without clean shutdown"))
        self.sink.set_node_alived(nid, False)
        return n

    def poll(self) -> int:
        try:
            return self._poll_once()
        except WatchLost as e:
            log.warnf("noticer watch lost (%s); resynchronizing", e)
            return self.resync()

    def resync(self) -> int:
        """Re-watch and deliver any pending notices from a re-list
        (notices are deleted after delivery, so the retry is safe;
        node-death events inside the lost window are checked against the
        alived mirror via the current node list)."""
        for w in (self._w_notice, self._w_nodes):
            try:
                w.close()
            except Exception:   # noqa: BLE001
                pass
        self._open_watches()
        n = 0
        for kv in self.store.get_prefix(self.ks.noticer):
            try:
                d = json.loads(kv.value)
            except json.JSONDecodeError:
                continue
            n += self._deliver(Notice(d.get("subject", ""),
                                      d.get("body", ""), d.get("to")))
            self.store.delete(kv.key)
        # nodes the mirror says are alive but whose lease key vanished
        # during the gap died uncleanly
        live = {kv.key[len(self.ks.node):]
                for kv in self.store.get_prefix(self.ks.node)}
        for mirror in self.sink.get_nodes():
            nid = mirror.get("id")
            if mirror.get("alived") and nid not in live:
                n += self._alert_node_down(nid)
        return n

    def _poll_once(self) -> int:
        n = 0
        for ev in self._w_notice.drain():
            if ev.type == DELETE:
                continue
            try:
                d = json.loads(ev.kv.value)
            except json.JSONDecodeError:
                continue
            n += self._deliver(Notice(d.get("subject", ""),
                                      d.get("body", ""), d.get("to")))
            self.store.delete(ev.kv.key)
        for ev in self._w_nodes.drain():
            if ev.type != DELETE:
                continue
            node_id = ev.kv.key[len(self.ks.node):]
            mirror = self.sink.get_node(node_id)
            if mirror and mirror.get("alived"):
                # lease expired but the node never said goodbye: a fault
                # (reference node.go:93-102 ISNodeFault)
                n += self._alert_node_down(node_id)
        return n

    def _deliver(self, notice: Notice) -> int:
        try:
            self.sender.send(notice)
        except Exception as e:  # noqa: BLE001 — notification must not crash
            log.errorf("noticer send failed: %s", e)
            return 0
        self.sent.append(notice)
        return 1

    def start(self):
        def run():
            while not self._stop.wait(0.5):
                self.poll()
                if hasattr(self.sender, "idle_check"):
                    self.sender.idle_check()
        self._thread = threading.Thread(target=run, daemon=True,
                                        name="noticer")
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=3)
