"""Health endpoints for the TCP servers (store, logd, sched).

Every server binary grows ``--health-port``: a tiny HTTP listener
serving

- ``GET /healthz`` — liveness: the process is up and serving its
  accept loop (always 200 once bound);
- ``GET /readyz``  — readiness: every registered check passes; 503
  with a JSON body NAMING the failing check otherwise
  (``{"ok": false, "checks": {"wal": {"ok": false, "detail": ...}}}``).

The web tier serves the same two routes on its existing HTTP port
(web/server.py readyz documents the shared contract); this module is
the twin for the line-JSON servers, which have no HTTP surface of
their own.  Checks are callables returning ``(ok, detail)`` — raising
counts as failing with the exception text as the detail.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple

from . import log

Check = Callable[[], Tuple[bool, str]]


def run_checks(checks: Dict[str, Check]) -> dict:
    out = {}
    for name, fn in checks.items():
        try:
            ok, detail = fn()
        except Exception as e:  # noqa: BLE001 — a raising check fails
            ok, detail = False, f"{type(e).__name__}: {e}"
        out[name] = {"ok": bool(ok), "detail": detail}
    return out


def wal_writable_check(path: Optional[str]) -> Check:
    """Shared readiness check: the server's WAL/DB sidecar directory
    still accepts writes (disk full / remount-ro are the outages this
    catches).  ``path`` None (in-memory server) always passes."""
    def check():
        if not path or path == ":memory:":
            return True, "in-memory"
        import os
        d = os.path.dirname(os.path.abspath(path)) or "."
        probe = os.path.join(d, f".cronsun-health-{os.getpid()}")
        with open(probe, "w") as f:
            f.write("ok")
        os.unlink(probe)
        return True, ""
    return check


def tcp_accept_check(host: str, port: int,
                     timeout: float = 2.0) -> Check:
    """Shared readiness check: the (possibly native) server still
    accepts TCP connections on its serving port."""
    def check():
        import socket
        with socket.create_connection((host, port), timeout=timeout):
            return True, ""
    return check


class HealthServer:
    """Serve /healthz + /readyz on ``port`` (0 picks a free port)."""

    def __init__(self, checks: Dict[str, Check],
                 host: str = "127.0.0.1", port: int = 0):
        self.checks = dict(checks)
        self.host, self.port = host, port
        self._httpd: Optional[ThreadingHTTPServer] = None

    def add_check(self, name: str, fn: Check):
        self.checks[name] = fn

    def start(self) -> "HealthServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path.split("?")[0] == "/healthz":
                    body, status = {"ok": True}, 200
                elif self.path.split("?")[0] == "/readyz":
                    checks = run_checks(server.checks)
                    ok = all(c["ok"] for c in checks.values())
                    body = {"ok": ok, "checks": checks}
                    status = 200 if ok else 503
                else:
                    body, status = {"error": "no such route"}, 404
                payload = json.dumps(body).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(target=self._httpd.serve_forever, daemon=True,
                         name="health-server").start()
        log.infof("health endpoints on %s:%d (/healthz /readyz)",
                  self.host, self.port)
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
